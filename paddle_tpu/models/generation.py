"""Compiled autoregressive generation: one XLA program for the whole decode.

The reference decodes eagerly — each step re-dispatches every op with a
grown cache (`LlamaForCausalLM.generate`-style loops; cache plumbing in
`paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu` and
`incubate/nn/functional/masked_multihead_attention`). On TPU, dynamic
shapes force a recompile per length, so the TPU-native design is the
static-shape serving loop:

  - the KV cache is ONE fixed buffer [L, B, Hkv, max_len, D] written with
    `dynamic_update_slice` at the current position (heads-major: the layout
    the attention kernels consume directly, so no per-step transpose);
  - attention masks invalid cache slots (iota > pos) instead of slicing a
    dynamic length — every step has identical shapes; on TPU the decode
    step (s_new=1) runs the Pallas decode-attention kernel
    (kernels/quantized_matmul.decode_attention), whose online max/sum stops
    at the position watermark instead of re-softmaxing the padded length;
  - the entire decode (prefill + lax.scan over steps + greedy/temperature/
    top-p sampling) traces into ONE `jax.jit`, so a 128-token generation
    is one device program launch, not 128 Python round-trips.

Works over the pure-functional param tree (`llama_functional`);
`params_from_layer` bridges a trained eager `LlamaForCausalLM` into it.
`quantize_params` converts the tree to weight-only int8 (QuantizedWeight
leaves); the same `generate` then streams int8 weights through the fused
Pallas dequant-matmul — the quantized-decode fast path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from typing import NamedTuple

from paddle_tpu.models import llama_functional as lf

__all__ = ["generate", "params_from_layer", "prefill", "decode_step",
           "paged_decode_step", "gpt_generate", "gpt_params_from_layer",
           "GPTGenArgs", "QuantizedWeight", "QuantizedKVPage",
           "quantize_params", "draft_from_params"]


class QuantizedWeight(NamedTuple):
    """Weight-only int8 leaf in a functional param tree: `q` int8 [..., K, N]
    with per-out-channel absmax `scale` [..., N] (dequant = q * scale / 127).
    A pytree node, so stacked [L, ...] leaves slice per layer under
    lax.scan like plain weights."""

    q: jax.Array
    scale: jax.Array


class QuantizedKVPage(NamedTuple):
    """int8 KV page-pool half: `q` int8 [..., num_pages, nkv, page_size,
    hd] with per-(page, kv-head) absmax `scale` [..., num_pages, nkv] f32
    (dequant = q * scale / 127 — the QuantizedWeight convention). A
    pytree node: the stacked [L, ...] pool slices per layer under
    lax.scan exactly like the bf16 pool arrays, and jit donation /
    shard_map specs treat (q, scale) as ONE pool operand — both leaves
    shard on the nkv axis, so the bf16 `P(None, None, mp)` pool spec
    applies to the pair as a pytree prefix unchanged."""

    q: jax.Array
    scale: jax.Array


def _kv_quant_write(pool, page, off, new):
    """Write one token's K or V rows `new` [b, nkv, hd] into an int8 page
    pool at (page[r], :, off[r]) keeping the per-(page, kv-head) absmax
    scale RUNNING: when a token's absmax exceeds the page's scale, the
    page's existing codes are re-scaled in-registers (round(q*old/new))
    before the write — no page is ever dequantized through HBM. Rows own
    their target pages exclusively (the host COW gate), except the null
    page 0, which is a garbage sink on every write path."""
    q, scale = pool
    b = page.shape[0]
    newf = new.astype(jnp.float32)
    tok_abs = jnp.max(jnp.abs(newf), axis=-1)              # [b, nkv]
    # positions fill pages sequentially, so a write at offset 0 is always
    # the page's FIRST live write — restart its running scale there
    # instead of inheriting a stale absmax from the page's previous owner
    # (pages return to the pool carrying old codes and scales)
    old_s = jnp.where(off[:, None] == 0, 0.0, scale[page])  # [b, nkv]
    new_s = jnp.maximum(old_s, tok_abs)
    safe = jnp.maximum(new_s, 1e-9)
    pg = q[page].astype(jnp.float32) * (old_s / safe)[:, :, None, None]
    pg = pg.at[jnp.arange(b), :, off].set(newf / safe[..., None] * 127.0)
    qpg = jnp.clip(jnp.round(pg), -127, 127).astype(jnp.int8)
    return QuantizedKVPage(q.at[page].set(qpg), scale.at[page].set(new_s))


def _quantize_weight(w):
    from paddle_tpu.kernels.quantized_matmul import quantize_absmax

    return QuantizedWeight(*quantize_absmax(w))


_QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_params(params):
    """Weight-only int8 quantization of a Llama functional param tree for
    decode: every per-layer matmul weight and the lm_head become
    QuantizedWeight leaves (embedding and norms stay float — a gather
    cannot fuse with the dequant). `generate` consumes the result
    unchanged; its matmuls stream int8 through the fused Pallas kernel."""
    layers = {k: (_quantize_weight(v) if k in _QUANT_KEYS else v)
              for k, v in params["layers"].items()}
    out = dict(params, layers=layers)
    out["lm_head"] = _quantize_weight(params["lm_head"])
    return out


def _wmm(x, w):
    """Matmul that understands QuantizedWeight leaves: float weights take
    the plain `@`, int8 weights stream through the fused dequant-matmul
    dispatch (Pallas on TPU, jnp elsewhere)."""
    if isinstance(w, QuantizedWeight):
        from paddle_tpu.kernels import quantized_matmul as qm

        return qm.weight_only_matmul(x, w.q, w.scale, out_dtype=x.dtype)
    return x @ w


def _tp_reduce(x, tp_axis):
    """Row-parallel output reduction for the tensor-parallel decode path:
    psum over the mp axis inside shard_map (the Megatron pattern
    llama_functional.decoder_layer uses for training), identity when the
    forward runs unsharded."""
    return x if tp_axis is None else jax.lax.psum(x, tp_axis)


def draft_from_params(params, args, num_layers):
    """Truncate a Llama functional tree to its first `num_layers` decoder
    layers (embedding/final_norm/lm_head shared) — a cheap draft model for
    speculative decoding whose early-layer predictions track the full
    target closely. Works on float and `quantize_params` trees (stacked
    QuantizedWeight leaves slice like plain weights). Returns
    (draft_params, draft_args)."""
    if not 1 <= num_layers <= args.num_layers:
        raise ValueError(
            f"draft must keep 1..{args.num_layers} layers, got {num_layers}")
    layers = jax.tree_util.tree_map(lambda x: x[:num_layers],
                                    params["layers"])
    return dict(params, layers=layers), args._replace(num_layers=num_layers)


def params_from_layer(model):
    """Stack an eager `LlamaForCausalLM`/`LlamaModel`'s weights into the
    functional tree `llama_functional` uses (layers stacked on a leading
    [L] dim). The transpose conventions match lf.init_params: every weight
    is [in, out]."""
    core = getattr(model, "model", model)
    lm_head = getattr(model, "lm_head", None)

    def arr(t):
        return t._data if hasattr(t, "_data") else jnp.asarray(t)

    layers = core.layers
    stacked = {}
    names = [("wq", lambda l: arr(l.self_attn.q_proj.weight)),
             ("wk", lambda l: arr(l.self_attn.k_proj.weight)),
             ("wv", lambda l: arr(l.self_attn.v_proj.weight)),
             ("wo", lambda l: arr(l.self_attn.o_proj.weight)),
             ("w_gate", lambda l: arr(l.mlp.gate_proj.weight)),
             ("w_up", lambda l: arr(l.mlp.up_proj.weight)),
             ("w_down", lambda l: arr(l.mlp.down_proj.weight)),
             ("ln1", lambda l: arr(l.input_layernorm.weight)),
             ("ln2", lambda l: arr(l.post_attention_layernorm.weight))]
    for key, get in names:
        stacked[key] = jnp.stack([get(l) for l in layers])
    return {
        "embedding": arr(core.embed_tokens.weight),
        "layers": stacked,
        "final_norm": arr(core.norm.weight),
        "lm_head": (arr(lm_head.weight) if lm_head is not None
                    else arr(core.embed_tokens.weight).T),
    }


def _cached_attention(q, cache_k, cache_v, pos):
    """Masked attention of q [b, s, nh, hd] over the full fixed-size cache
    [b, nkv, max_len, hd] (invalid slots masked by position — static shapes
    every step). Shared by the Llama and GPT decode layers. The decode step
    (s == 1) dispatches to the Pallas decode-attention kernel when
    supported: single query against the cache, online max/sum bounded to
    the valid prefix, GQA without repeating kv heads.

    pos: scalar (every row at the same depth — the compiled generate), or
    an int32 [b] vector of per-row positions (continuous-batching decode:
    each slot at its own depth; with s > 1 query row i of batch row r sits
    at pos[r] + i — the speculative-verify window)."""
    b, s, nh, hd = q.shape
    nkv, max_len = cache_k.shape[1], cache_k.shape[2]
    from paddle_tpu.kernels import quantized_matmul as qm

    if s == 1:
        if qm.fused_enabled() and qm.decode_supported(
                q.shape, cache_k.shape, q.dtype.itemsize):
            return qm.decode_attention(q, cache_k, cache_v, pos)
    elif qm.fused_enabled() and qm.window_supported(
            q.shape, cache_k.shape, q.dtype.itemsize):
        # a SHORT query window at a traced offset — the chunk-offset
        # prefill tail and the speculative-verify window ride the Pallas
        # window kernel (online max/sum bounded to the last query's
        # watermark) instead of re-softmaxing the padded cache length
        return qm.window_decode_attention(q, cache_k, cache_v, pos)
    if nkv != nh:
        rep = nh // nkv
        kh = jnp.repeat(cache_k, rep, axis=1)
        vh = jnp.repeat(cache_v, rep, axis=1)
    else:
        kh, vh = cache_k, cache_v
    qh = jnp.swapaxes(q, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(hd)
    key_pos = jax.lax.broadcasted_iota(jnp.int32, (1, 1, s, max_len), 3)
    row_iota = jax.lax.broadcasted_iota(jnp.int32, (1, 1, s, max_len), 2)
    if jnp.ndim(pos) == 1:
        query_pos = jnp.asarray(pos).reshape(b, 1, 1, 1) + row_iota
    else:
        query_pos = pos + row_iota
    scores = jnp.where(key_pos <= query_pos, scores, -1e30)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    attn = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vh.dtype), vh)
    return jnp.swapaxes(attn, 1, 2)


def _rope_rows(q, k, cos_r, sin_r):
    """RoPE at per-row positions: q/k [b, 1, nh, hd], cos_r/sin_r [b, hd]
    (the rows of the RoPE tables gathered at each row's own position) —
    the same rotate-half math as lf.apply_rope, broadcast over batch
    instead of sequence."""
    return lf.apply_rope_bcast(q, k, cos_r[:, None, None, :],
                               sin_r[:, None, None, :])


def _layer_step(lp, h, cache_k, cache_v, pos, cos, sin, args,
                tp_axis=None, tp_degree=1):
    """One decoder layer over `h` [b, s, hid] with a fixed-size cache.

    prefill (pos == 0, s == prompt len): causal attention within the
    block, cache slots [0, s) written. decode (s == 1): attend over
    cache[: pos+1] via masking, slot [pos] written. Both are the same
    masking rule: key_pos <= pos + query_row.

    pos may be an int32 [b] vector (requires s == 1): every row sits at its
    own position — per-row RoPE, per-row cache-slot writes, per-row
    attention masking. This is the continuous-batching decode step.

    tp_axis/tp_degree: when set, this body runs inside shard_map over a
    tensor-parallel mesh axis — lp holds the Megatron shards (wq/wk/wv/
    w_gate/w_up split on the out dim, wo/w_down on the in dim), the cache
    holds this device's nkv/tp_degree heads, and the row-parallel outputs
    are psum-reduced so `h` stays replicated."""
    b, s = h.shape[0], h.shape[1]
    nh = args.num_heads // tp_degree
    nkv = args.num_kv_heads // tp_degree
    hd = args.hidden_size // args.num_heads

    hin = lf.rms_norm(h, lp["ln1"], args.rms_eps)
    q = _wmm(hin, lp["wq"]).reshape(b, s, nh, hd)
    k = _wmm(hin, lp["wk"]).reshape(b, s, nkv, hd)
    v = _wmm(hin, lp["wv"]).reshape(b, s, nkv, hd)
    if jnp.ndim(pos) == 1:
        if s != 1:
            raise ValueError("per-row pos vector requires s == 1 "
                             f"(got s={s})")
        q, k = _rope_rows(q, k, jnp.take(cos, pos, axis=0),
                          jnp.take(sin, pos, axis=0))

        # cache [b, nkv, max_len, hd]: each row's new kv lands at that
        # row's own position
        def write_row(c, new, p):
            return jax.lax.dynamic_update_slice_in_dim(c, new, p, axis=1)

        cache_k = jax.vmap(write_row)(cache_k, jnp.swapaxes(k, 1, 2), pos)
        cache_v = jax.vmap(write_row)(cache_v, jnp.swapaxes(v, 1, 2), pos)
    else:
        q, k = lf.apply_rope(q, k,
                             jax.lax.dynamic_slice_in_dim(cos, pos, s, 0),
                             jax.lax.dynamic_slice_in_dim(sin, pos, s, 0))
        # cache is heads-major [b, nkv, max_len, hd]; write new slots at pos
        cache_k = jax.lax.dynamic_update_slice_in_dim(
            cache_k, jnp.swapaxes(k, 1, 2), pos, axis=2)
        cache_v = jax.lax.dynamic_update_slice_in_dim(
            cache_v, jnp.swapaxes(v, 1, 2), pos, axis=2)

    attn = _cached_attention(q, cache_k, cache_v, pos)
    attn = attn.reshape(b, s, nh * hd)
    h = h + _tp_reduce(_wmm(attn, lp["wo"]), tp_axis)

    hin = lf.rms_norm(h, lp["ln2"], args.rms_eps)
    act = jax.nn.silu(_wmm(hin, lp["w_gate"])) * _wmm(hin, lp["w_up"])
    h = h + _tp_reduce(_wmm(act, lp["w_down"]), tp_axis)
    return h, cache_k, cache_v


def _forward_cached(params, ids, caches_k, caches_v, pos, cos, sin, args,
                    last_idx=None, tp_axis=None, tp_degree=1):
    """ids [b, s] -> (next-token logits [b, vocab], new caches).

    last_idx: optional traced per-row (or scalar) index of the LAST REAL
    token in each row — serving prefills pad prompts up to a length bucket,
    so the next-token logits live at true_len-1, not at s-1. None keeps the
    plain h[:, -1] gather."""
    h = jnp.take(params["embedding"], ids, axis=0)

    def step(carry, xs):
        h = carry
        lp, ck, cv = xs
        h, ck, cv = _layer_step(lp, h, ck, cv, pos, cos, sin, args,
                                tp_axis, tp_degree)
        return h, (ck, cv)

    h, (new_k, new_v) = jax.lax.scan(step, h,
                                     (params["layers"], caches_k, caches_v))
    h = lf.rms_norm(h, params["final_norm"], args.rms_eps)
    if last_idx is None:
        hl = h[:, -1, :]
    else:
        idx = jnp.broadcast_to(jnp.asarray(last_idx, jnp.int32).reshape(-1),
                               (h.shape[0],))
        hl = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0, :]
    logits = _wmm(hl, params["lm_head"])
    return logits.astype(jnp.float32), new_k, new_v


def _layer_step_paged(lp, h, pool_k_l, pool_v_l, bt, pos, cos, sin, args,
                      page_size, tp_axis=None, tp_degree=1):
    """One decoder layer's decode step (s == 1) over a PAGED KV cache.

    pool_k_l/pool_v_l: this layer's page pool [num_pages, nkv, ps, hd];
    bt: int32 block tables [b, P] (page i of row r holds positions
    [i*ps, (i+1)*ps) of that row — unused entries point at the null page);
    pos: int32 [b] per-row write positions. Each row's new k/v is
    SCATTERED to (bt[r, pos[r]//ps], pos[r] % ps) — write-before-attend,
    like the stripe path — then attention gathers K/V through the block
    table (Pallas paged kernel on TPU, jnp gather elsewhere).

    tp_axis/tp_degree: shard_map tensor parallelism — weight shards as in
    `_layer_step`, the page pool sharded on nkv (block tables replicated,
    every device walks the same tables over its own kv-head slice)."""
    b, s = h.shape[0], h.shape[1]
    if s != 1:
        raise ValueError(f"paged decode requires s == 1 (got s={s})")
    nh = args.num_heads // tp_degree
    nkv = args.num_kv_heads // tp_degree
    hd = args.hidden_size // args.num_heads
    ps = page_size

    hin = lf.rms_norm(h, lp["ln1"], args.rms_eps)
    q = _wmm(hin, lp["wq"]).reshape(b, 1, nh, hd)
    k = _wmm(hin, lp["wk"]).reshape(b, 1, nkv, hd)
    v = _wmm(hin, lp["wv"]).reshape(b, 1, nkv, hd)
    q, k = _rope_rows(q, k, jnp.take(cos, pos, axis=0),
                      jnp.take(sin, pos, axis=0))

    # per-row scatter into the pool: rows own their tail page exclusively
    # (the host-side COW gate guarantees it), so writes never collide on a
    # live page
    page = jnp.take_along_axis(bt, (pos // ps)[:, None], axis=1)[:, 0]
    off = pos % ps
    quantized = isinstance(pool_k_l, QuantizedKVPage)
    if quantized:
        pool_k_l = _kv_quant_write(pool_k_l, page, off, k[:, 0])
        pool_v_l = _kv_quant_write(pool_v_l, page, off, v[:, 0])
        kq, ks = pool_k_l
        vq, vs = pool_v_l
    else:
        pool_k_l = pool_k_l.at[page, :, off].set(k[:, 0])
        pool_v_l = pool_v_l.at[page, :, off].set(v[:, 0])
        kq, ks, vq, vs = pool_k_l, None, pool_v_l, None

    from paddle_tpu.kernels import quantized_matmul as qm

    if qm.fused_enabled() and qm.paged_decode_supported(
            q.shape, kq.shape, bt.shape, kq.dtype.itemsize):
        attn = qm.paged_decode_attention(q, kq, vq, bt, pos,
                                         k_scale=ks, v_scale=vs)
    else:
        # gather pages into the contiguous per-row layout (dequantized
        # under an int8 pool) and reuse the stripe attention (jnp mask
        # fallback; contiguous Pallas kernel if eligible) — table order
        # IS sequence order, so positions line up
        attn = _cached_attention(
            q, qm.paged_gather(kq, bt, scale=ks, out_dtype=q.dtype),
            qm.paged_gather(vq, bt, scale=vs, out_dtype=q.dtype), pos)
    h = h + _tp_reduce(_wmm(attn.reshape(b, 1, nh * hd), lp["wo"]), tp_axis)

    hin = lf.rms_norm(h, lp["ln2"], args.rms_eps)
    act = jax.nn.silu(_wmm(hin, lp["w_gate"])) * _wmm(hin, lp["w_up"])
    h = h + _tp_reduce(_wmm(act, lp["w_down"]), tp_axis)
    return h, pool_k_l, pool_v_l


def _layer_step_paged_verify(lp, h, pool_k_l, pool_v_l, bt, pos, limit,
                             cos, sin, args, page_size, tp_axis=None,
                             tp_degree=1):
    """One decoder layer over a SPECULATION WINDOW of s draft tokens
    against the paged cache: query i of row r sits at position pos[r]+i.

    The s new k/v of each row scatter into its tail pages
    (write-before-attend; the host pre-allocates pages through
    pos+s-1, COW-cleared). Writes past `limit[r]` — the row's last legal
    KV index, i.e. beyond its admission-time page reservation — are
    REDIRECTED to the null page (the garbage sink): a row about to finish
    never touches pages it does not own, and the position mask keeps the
    skipped slots unread. Attention gathers the row's whole table and
    masks per row per query (`_cached_attention`'s vector-pos branch)."""
    b, s = h.shape[0], h.shape[1]
    nh = args.num_heads // tp_degree
    nkv = args.num_kv_heads // tp_degree
    hd = args.hidden_size // args.num_heads
    ps = page_size

    hin = lf.rms_norm(h, lp["ln1"], args.rms_eps)
    q = _wmm(hin, lp["wq"]).reshape(b, s, nh, hd)
    k = _wmm(hin, lp["wk"]).reshape(b, s, nkv, hd)
    v = _wmm(hin, lp["wv"]).reshape(b, s, nkv, hd)
    prow = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]  # [b, s]
    cos_r = jnp.take(cos, prow, axis=0)                      # [b, s, hd]
    sin_r = jnp.take(sin, prow, axis=0)
    q, k = lf.apply_rope_bcast(q, k, cos_r[:, :, None, :],
                               sin_r[:, :, None, :])

    page = jnp.take_along_axis(bt, prow // ps, axis=1)       # [b, s]
    page = jnp.where(prow <= limit[:, None], page, 0)        # null-page sink
    off = prow % ps
    if isinstance(pool_k_l, QuantizedKVPage):
        # token-at-a-time running-absmax writes (s is tiny — the draft
        # window) so a window straddling a page boundary re-scales each
        # touched page exactly once per token that exceeds its scale
        for i in range(s):
            pool_k_l = _kv_quant_write(pool_k_l, page[:, i], off[:, i],
                                       k[:, i])
            pool_v_l = _kv_quant_write(pool_v_l, page[:, i], off[:, i],
                                       v[:, i])
        kq, ks = pool_k_l
        vq, vs = pool_v_l
    else:
        pool_k_l = pool_k_l.at[page.reshape(-1), :, off.reshape(-1)].set(
            k.reshape(b * s, nkv, hd))
        pool_v_l = pool_v_l.at[page.reshape(-1), :, off.reshape(-1)].set(
            v.reshape(b * s, nkv, hd))
        kq, ks, vq, vs = pool_k_l, None, pool_v_l, None

    from paddle_tpu.kernels import quantized_matmul as qm

    # gather the row's table and run the window through the shared masked
    # attention (its vector-pos s>1 branch: query i of row r at pos[r]+i).
    # s is tiny (draft length + 1), so gather-then-mask is the dispatch on
    # every backend; a fused window kernel is a follow-up once
    # TPU-measured numbers justify it
    attn = _cached_attention(
        q, qm.paged_gather(kq, bt, scale=ks, out_dtype=q.dtype),
        qm.paged_gather(vq, bt, scale=vs, out_dtype=q.dtype), pos)
    h = h + _tp_reduce(_wmm(attn.reshape(b, s, nh * hd), lp["wo"]), tp_axis)

    hin = lf.rms_norm(h, lp["ln2"], args.rms_eps)
    act = jax.nn.silu(_wmm(hin, lp["w_gate"])) * _wmm(hin, lp["w_up"])
    h = h + _tp_reduce(_wmm(act, lp["w_down"]), tp_axis)
    return h, pool_k_l, pool_v_l


def _paged_forward_decode(params, ids, pool_k, pool_v, bt, pos, cos, sin,
                          args, page_size, tp_axis=None, tp_degree=1):
    """ids [b, 1] -> (next-token logits [b, vocab], new pools). The paged
    analogue of `_forward_cached`'s decode step: pools are [L, num_pages,
    nkv, ps, hd] and slice per layer under the same lax.scan."""
    h = jnp.take(params["embedding"], ids, axis=0)

    def step(carry, xs):
        h = carry
        lp, pk, pv = xs
        h, pk, pv = _layer_step_paged(lp, h, pk, pv, bt, pos, cos, sin,
                                      args, page_size, tp_axis, tp_degree)
        return h, (pk, pv)

    h, (new_k, new_v) = jax.lax.scan(step, h,
                                     (params["layers"], pool_k, pool_v))
    h = lf.rms_norm(h, params["final_norm"], args.rms_eps)
    logits = _wmm(h[:, -1, :], params["lm_head"])
    return logits.astype(jnp.float32), new_k, new_v


def _paged_forward_verify(params, ids, pool_k, pool_v, bt, pos, limit,
                          cos, sin, args, page_size, tp_axis=None,
                          tp_degree=1):
    """Speculative-verify forward: ids [b, s] (the last committed token
    followed by s-1 draft tokens, row r's token i at position pos[r]+i)
    -> (logits [b, s, vocab] at EVERY window position, new pools). One
    batched program scores a whole draft window — the target-model half
    of speculative decoding (Leviathan et al.; greedy exact-match
    acceptance happens on host)."""
    h = jnp.take(params["embedding"], ids, axis=0)

    def step(carry, xs):
        h = carry
        lp, pk, pv = xs
        h, pk, pv = _layer_step_paged_verify(
            lp, h, pk, pv, bt, pos, limit, cos, sin, args, page_size,
            tp_axis, tp_degree)
        return h, (pk, pv)

    h, (new_k, new_v) = jax.lax.scan(step, h,
                                     (params["layers"], pool_k, pool_v))
    h = lf.rms_norm(h, params["final_norm"], args.rms_eps)
    logits = _wmm(h, params["lm_head"])
    return logits.astype(jnp.float32), new_k, new_v


def paged_decode_step(params, args, token, pool_k, pool_v, block_tables,
                      pos, page_size):
    """One continuous-batching decode step over a paged KV cache: token
    [b] at per-row positions pos [b], K/V stored as pages [L, num_pages,
    nkv, page_size, hd] indexed through block_tables [b, P]. Rows are
    independent; unused/inactive table entries must point at a valid page
    index (conventionally the null page 0) and are never read thanks to
    the position mask. float and `quantize_params` int8 trees both work —
    every matmul rides the fused dequant-matmul dispatch — and the pools
    may be `QuantizedKVPage` pairs (int8 pages + per-(page, kv-head)
    scales): writes then quantize in place and attention dequantizes
    in-registers."""
    hd = args.hidden_size // args.num_heads
    P = block_tables.shape[1]
    cos, sin = lf.rope_tables(P * int(page_size), hd, args.rope_theta)
    return _paged_forward_decode(
        params, jnp.asarray(token)[:, None], pool_k, pool_v,
        jnp.asarray(block_tables, jnp.int32), jnp.asarray(pos, jnp.int32),
        cos, sin, args, int(page_size))


def _row_keys(seeds, pos):
    """Per-request sampling keys [b]: fold (seed, position) into a fixed
    base key — a request's randomness is a pure function of its own seed
    and the position being sampled, independent of batch composition.
    THE one derivation shared by `generate(seeds=...)` and the serving
    engines' per-slot sampler (the documented common key stream)."""
    base = jax.vmap(
        lambda s: jax.random.fold_in(jax.random.key(0), s))(seeds)
    return jax.vmap(jax.random.fold_in)(
        base, jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1),
                               (base.shape[0],)))


def _warp_logits(logits, temperature, top_p, top_k):
    """The per-request logit warp shared by `_sample` and rejection-
    sampling speculation: temperature scale, then top-k mask, then
    nucleus mask over the k-survivors (-1e30 for killed entries).
    Returns (masked [b, vocab], greedy_rows [b]). Rejection sampling
    needs the warped DISTRIBUTION itself (softmax of `masked`), not just
    a draw — and draft/target must warp with bit-identical math for the
    acceptance ratio p_target/p_draft to mean anything, hence the single
    shared implementation."""
    b, vocab = logits.shape
    t = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    greedy_rows = t <= 0.0
    scaled = logits / jnp.where(greedy_rows, 1.0, t)[:, None]

    # top-k: mask everything below the k-th largest (k <= 0 or >= vocab
    # keeps all). Computed on the DESCENDING sort shared with top-p.
    k_vec = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    k_eff = jnp.where(k_vec <= 0, vocab, jnp.minimum(k_vec, vocab))
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
    rank = jax.lax.broadcasted_iota(jnp.int32, (b, vocab), 1)
    kth = jnp.take_along_axis(sorted_logits, (k_eff - 1)[:, None], axis=-1)
    sorted_masked = jnp.where(rank < k_eff[:, None], sorted_logits, -1e30)

    # nucleus mask over the k-survivors (a no-op when top_p == 1.0: the
    # cutoff lands on the smallest surviving logit and everything stays)
    p_vec = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32).reshape(-1),
                             (b,))[:, None]
    probs = jax.nn.softmax(sorted_masked, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cum < p_vec, axis=-1, keepdims=True)
    cutoff = jnp.take_along_axis(sorted_masked, cutoff_idx, axis=-1)
    masked = jnp.where((scaled >= cutoff) & (scaled >= kth), scaled, -1e30)
    return masked, greedy_rows


def _sample(logits, sample, temperature, top_p, key, top_k=0,
            row_keys=None):
    """The per-request sampler. `sample` is the only STATIC switch (argmax
    vs categorical program structure); temperature/top_p/top_k are traced
    scalars OR per-row [b] vectors, so serving can vary them per request —
    per SLOT — without recompiling the decode program. Rows with
    temperature <= 0 stay exactly greedy (argmax), which is what keeps a
    greedy request's output bit-identical inside a mixed sampling batch.

    top_k <= 0 disables the top-k mask (all of vocab survives); top_p and
    top_k compose (k-mask first, nucleus over what remains — the
    huggingface/vLLM order). Sampling draws from `key` (one shared PRNG
    stream, split by the caller per step) or, when `row_keys` [b] is
    given, per-row gumbel-max draws — the per-request-seed path, where a
    request's randomness depends only on its own seed and position, not
    on which other requests share its batch."""
    if not sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    masked, greedy_rows = _warp_logits(logits, temperature, top_p, top_k)

    if row_keys is not None:
        # gumbel-max: argmax(logits + g) ~ categorical(softmax(logits)),
        # one independent draw per row from that row's own key
        vocab = logits.shape[-1]
        u = jax.vmap(lambda k_: jax.random.uniform(
            k_, (vocab,), jnp.float32, minval=1e-20, maxval=1.0))(row_keys)
        drawn = jnp.argmax(masked - jnp.log(-jnp.log(u)), axis=-1)
    else:
        drawn = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(greedy_rows, jnp.argmax(logits, axis=-1),
                     drawn).astype(jnp.int32)


def _decode_loop(fwd, prompt_ids, ck, cv, max_new_tokens, sample,
                 temperature, top_p, key, use_eos=False, eos_id=0, pad_id=0,
                 top_k=0, seeds=None):
    """Shared prefill->sample->scan->concat driver (traced inside the
    per-architecture jit): fwd(ids, ck, cv, pos) -> (logits, ck, cv).

    use_eos (the only STATIC eos switch — program structure): rows that
    emit eos_id are DONE and emit pad_id from then on (the output stays a
    static [b, s + max_new_tokens] rectangle; per-row dynamic lengths
    would defeat the one-program design). eos_id/pad_id themselves are
    traced operands, so changing token ids never recompiles. The scan
    still runs max_new_tokens steps — XLA cannot early-exit a compiled
    loop — but finished rows carry a done mask, matching the reference's
    eager stopping criterion semantically."""
    b, s = prompt_ids.shape

    def rkeys(pos):
        # per-request seeds: a row's key depends only on (its seed, the
        # position being sampled) — stable across batch compositions
        return None if seeds is None else _row_keys(seeds, pos)

    logits, ck, cv = fwd(prompt_ids, ck, cv, 0)
    key, sub = jax.random.split(key)
    first = _sample(logits, sample, temperature, top_p, sub, top_k,
                    rkeys(jnp.int32(s)))
    done0 = first == eos_id if use_eos else jnp.zeros((b,), bool)
    if max_new_tokens == 1:
        return jnp.concatenate([prompt_ids, first[:, None]], axis=1)

    def step(carry, xs):
        token, ck, cv, pos, key, done = carry
        logits, ck, cv = fwd(token[:, None], ck, cv, pos)
        key, sub = jax.random.split(key)
        nxt = _sample(logits, sample, temperature, top_p, sub, top_k,
                      rkeys(pos + 1))
        if use_eos:
            nxt = jnp.where(done, pad_id.astype(jnp.int32), nxt)
            done = done | (nxt == eos_id)
        return (nxt, ck, cv, pos + 1, key, done), token

    (last, *_), toks = jax.lax.scan(
        step, (first, ck, cv, jnp.int32(s), key, done0), None,
        length=max_new_tokens - 1)
    new_tokens = jnp.concatenate([jnp.swapaxes(toks, 0, 1), last[:, None]],
                                 axis=1)
    return jnp.concatenate([prompt_ids, new_tokens], axis=1)


def _init_cache(params, args, b, max_len):
    """Fixed-size KV cache buffers [L, b, nkv, max_len, hd] + RoPE tables —
    shared by the public prefill/decode_step incremental API and the
    compiled generate."""
    L = lf.stack_leading_dim(params["layers"])
    hd = args.hidden_size // args.num_heads
    ck = jnp.zeros((L, b, args.num_kv_heads, max_len, hd),
                   params["embedding"].dtype)
    cv = jnp.zeros_like(ck)
    cos, sin = lf.rope_tables(max_len, hd, args.rope_theta)
    return ck, cv, cos, sin


def prefill(params, args, prompt_ids, max_len):
    """Run the prompt through the model once, filling the caches.
    Returns (next_logits [b, vocab], caches_k, caches_v) with caches
    [L, b, nkv, max_len, hd]."""
    b, s = prompt_ids.shape
    ck, cv, cos, sin = _init_cache(params, args, b, max_len)
    return _forward_cached(params, prompt_ids, ck, cv, 0, cos, sin, args)


def decode_step(params, args, token, caches_k, caches_v, pos, max_len):
    """One incremental step: token [b] at position pos.

    pos: scalar (uniform batch — every row at the same depth), or an int32
    [b] vector of PER-ROW positions: each row attends its own valid prefix
    [0, pos[i]] and writes its kv at pos[i]. The vector form is the
    continuous-batching decode step (paddle_tpu.serving): slots admitted at
    different times sit at different sequence depths inside one batched
    program. Rows are independent — an inactive/garbage slot cannot perturb
    the others."""
    hd = args.hidden_size // args.num_heads
    cos, sin = lf.rope_tables(max_len, hd, args.rope_theta)
    if jnp.ndim(pos) == 1:
        pos = jnp.asarray(pos, jnp.int32)
    return _forward_cached(params, token[:, None], caches_k, caches_v, pos,
                           cos, sin, args)


def generate(params, args, prompt_ids, max_new_tokens=32, temperature=0.0,
             top_p=1.0, key=None, eos_token_id=None, pad_token_id=0,
             top_k=0, seeds=None):
    """Whole generation as one compiled program.

    prompt_ids: [b, s] int32. Returns [b, s + max_new_tokens] int32.
    temperature 0 = greedy; top_p < 1 = nucleus sampling; top_k > 0 keeps
    only the k largest logits. temperature/top_p/top_k are traced and may
    be scalars or per-row [b] vectors (vary per call and per request
    without recompiling); only the greedy/sampling mode switch and shapes
    are compile-time.
    seeds: optional per-row int seeds [b]. Each row then samples from its
    own (seed, position)-derived PRNG stream — the same row with the same
    seed reproduces its tokens regardless of what else is in the batch.
    eos_token_id: rows that emit it produce pad_token_id afterwards (the
    output stays rectangular)."""
    if max_new_tokens <= 0:
        return jnp.asarray(prompt_ids)
    if key is None:
        key = jax.random.key(0)
    sample = bool(np.any(np.asarray(temperature) != 0.0))
    use_eos = eos_token_id is not None
    return _generate_jit(params, args, jnp.asarray(prompt_ids),
                         max_new_tokens, sample,
                         jnp.asarray(temperature if sample else 1.0,
                                     jnp.float32),
                         jnp.asarray(top_p, jnp.float32), key, use_eos,
                         jnp.int32(eos_token_id if use_eos else 0),
                         jnp.int32(pad_token_id),
                         jnp.asarray(top_k, jnp.int32),
                         (None if seeds is None
                          else jnp.asarray(seeds, jnp.int32)))


@functools.partial(jax.jit, static_argnames=("args", "max_new_tokens",
                                             "sample", "use_eos"))
def _generate_jit(params, args, prompt_ids, max_new_tokens, sample,
                  temperature, top_p, key, use_eos=False, eos_id=0,
                  pad_id=0, top_k=0, seeds=None):
    b, s = prompt_ids.shape
    max_len = s + max_new_tokens
    ck, cv, cos, sin = _init_cache(params, args, b, max_len)

    def fwd(ids, ck, cv, pos):
        return _forward_cached(params, ids, ck, cv, pos, cos, sin, args)

    return _decode_loop(fwd, prompt_ids, ck, cv, max_new_tokens, sample,
                        temperature, top_p, key, use_eos,
                        jnp.asarray(eos_id), jnp.asarray(pad_id),
                        jnp.asarray(top_k), seeds)


# --------------------------------------------------------------------------
# GPT-2 family (models/gpt.py): pre-LN blocks, learned positions, tied head
# --------------------------------------------------------------------------


class GPTGenArgs(NamedTuple):
    """Static (hashable) GPT shape for the compiled decode."""

    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    max_position_embeddings: int
    ln_eps: float = 1e-5

    @staticmethod
    def from_config(cfg):
        return GPTGenArgs(cfg.vocab_size, cfg.hidden_size,
                          cfg.num_hidden_layers, cfg.num_attention_heads,
                          cfg.max_position_embeddings,
                          getattr(cfg, "layer_norm_eps", 1e-5))


def gpt_params_from_layer(model):
    """Stack an eager `GPTForCausalLM`/`GPTModel` into a functional tree
    (weights [in, out]; biases as-is; layers stacked on a leading [L])."""
    core = getattr(model, "gpt", model)

    def arr(t):
        return t._data if hasattr(t, "_data") else jnp.asarray(t)

    names = [
        ("ln1_w", lambda l: arr(l.ln1.weight)),
        ("ln1_b", lambda l: arr(l.ln1.bias)),
        ("wq", lambda l: arr(l.attn.q_proj.weight)),
        ("bq", lambda l: arr(l.attn.q_proj.bias)),
        ("wk", lambda l: arr(l.attn.k_proj.weight)),
        ("bk", lambda l: arr(l.attn.k_proj.bias)),
        ("wv", lambda l: arr(l.attn.v_proj.weight)),
        ("bv", lambda l: arr(l.attn.v_proj.bias)),
        ("wo", lambda l: arr(l.attn.out_proj.weight)),
        ("bo", lambda l: arr(l.attn.out_proj.bias)),
        ("ln2_w", lambda l: arr(l.ln2.weight)),
        ("ln2_b", lambda l: arr(l.ln2.bias)),
        ("fc1_w", lambda l: arr(l.fc1.weight)),
        ("fc1_b", lambda l: arr(l.fc1.bias)),
        ("fc2_w", lambda l: arr(l.fc2.weight)),
        ("fc2_b", lambda l: arr(l.fc2.bias)),
    ]
    stacked = {k: jnp.stack([get(l) for l in core.layers])
               for k, get in names}
    return {
        "word_emb": arr(core.embeddings.word_embeddings.weight),
        "pos_emb": arr(core.embeddings.position_embeddings.weight),
        "layers": stacked,
        "lnf_w": arr(core.final.ln_f.weight),
        "lnf_b": arr(core.final.ln_f.bias),
    }


def _layer_norm(x, w, b, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean((x32 - mu) ** 2, axis=-1, keepdims=True)
    return (((x32 - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b)


def _gpt_layer_step(lp, h, cache_k, cache_v, pos, args: GPTGenArgs):
    b, s = h.shape[0], h.shape[1]
    nh = args.num_heads
    hd = args.hidden_size // nh

    hin = _layer_norm(h, lp["ln1_w"], lp["ln1_b"], args.ln_eps)
    q = (hin @ lp["wq"] + lp["bq"]).reshape(b, s, nh, hd)
    k = (hin @ lp["wk"] + lp["bk"]).reshape(b, s, nh, hd)
    v = (hin @ lp["wv"] + lp["bv"]).reshape(b, s, nh, hd)
    if jnp.ndim(pos) == 1:
        # per-row positions (serving decode; s must be 1) — the same
        # vmapped per-row cache write the llama `_layer_step` uses
        write = jax.vmap(lambda c, new, p: jax.lax.dynamic_update_slice_in_dim(
            c, new, p, axis=1))
        cache_k = write(cache_k, jnp.swapaxes(k, 1, 2), pos)
        cache_v = write(cache_v, jnp.swapaxes(v, 1, 2), pos)
    else:
        cache_k = jax.lax.dynamic_update_slice_in_dim(
            cache_k, jnp.swapaxes(k, 1, 2), pos, axis=2)
        cache_v = jax.lax.dynamic_update_slice_in_dim(
            cache_v, jnp.swapaxes(v, 1, 2), pos, axis=2)
    attn = _cached_attention(q, cache_k, cache_v, pos).reshape(b, s, nh * hd)
    h = h + (attn @ lp["wo"] + lp["bo"])

    hin = _layer_norm(h, lp["ln2_w"], lp["ln2_b"], args.ln_eps)
    act = jax.nn.gelu(hin @ lp["fc1_w"] + lp["fc1_b"], approximate=False)
    h = h + (act @ lp["fc2_w"] + lp["fc2_b"])
    return h, cache_k, cache_v


def _gpt_forward_cached(params, ids, caches_k, caches_v, pos,
                        args: GPTGenArgs, last_idx=None):
    """pos: scalar, or int32 [b] per-row positions (serving decode, s=1).
    last_idx: optional per-row index of the last REAL token (serving
    prefills pad to a length bucket) — None keeps the h[:, -1] gather."""
    b, s = ids.shape
    if jnp.ndim(pos) == 1:
        positions = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
        pe = jnp.take(params["pos_emb"], positions, axis=0)
    else:
        positions = pos + jnp.arange(s, dtype=jnp.int32)
        pe = jnp.take(params["pos_emb"], positions, axis=0)[None]
    h = jnp.take(params["word_emb"], ids, axis=0) + pe

    def step(carry, lp_kv):
        h = carry
        lp, ck, cv = lp_kv
        h, ck, cv = _gpt_layer_step(lp, h, ck, cv, pos, args)
        return h, (ck, cv)

    h, (new_k, new_v) = jax.lax.scan(step, h,
                                     (params["layers"], caches_k, caches_v))
    h = _layer_norm(h, params["lnf_w"], params["lnf_b"], args.ln_eps)
    if last_idx is None:
        hl = h[:, -1, :]
    else:
        idx = jnp.broadcast_to(jnp.asarray(last_idx, jnp.int32).reshape(-1),
                               (h.shape[0],))
        hl = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0, :]
    logits = hl @ params["word_emb"].T  # tied head
    return logits.astype(jnp.float32), new_k, new_v


def gpt_generate(params, args: GPTGenArgs, prompt_ids, max_new_tokens=32,
                 temperature=0.0, top_p=1.0, key=None, eos_token_id=None,
                 pad_token_id=0):
    """GPT-2 whole-generation-as-one-program (same machinery as the Llama
    `generate`, incl. eos early-stop semantics; learned positions bound
    max_len by args.max_position_embeddings)."""
    if max_new_tokens <= 0:
        return jnp.asarray(prompt_ids)
    if key is None:
        key = jax.random.key(0)
    b, s = np.asarray(prompt_ids).shape
    if s + max_new_tokens > args.max_position_embeddings:
        raise ValueError(
            f"prompt {s} + max_new_tokens {max_new_tokens} exceeds the "
            f"learned position table ({args.max_position_embeddings})")
    sample = bool(np.asarray(temperature) != 0.0)
    use_eos = eos_token_id is not None
    return _gpt_generate_jit(params, args, jnp.asarray(prompt_ids),
                             max_new_tokens, sample,
                             jnp.float32(temperature if sample else 1.0),
                             jnp.float32(top_p), key, use_eos,
                             jnp.int32(eos_token_id if use_eos else 0),
                             jnp.int32(pad_token_id))


@functools.partial(jax.jit, static_argnames=("args", "max_new_tokens",
                                             "sample", "use_eos"))
def _gpt_generate_jit(params, args, prompt_ids, max_new_tokens, sample,
                      temperature, top_p, key, use_eos=False, eos_id=0,
                      pad_id=0):
    b, s = prompt_ids.shape
    max_len = s + max_new_tokens
    L = args.num_layers
    hd = args.hidden_size // args.num_heads
    ck = jnp.zeros((L, b, args.num_heads, max_len, hd),
                   params["word_emb"].dtype)
    cv = jnp.zeros_like(ck)

    def fwd(ids, ck, cv, pos):
        return _gpt_forward_cached(params, ids, ck, cv, pos, args)

    return _decode_loop(fwd, prompt_ids, ck, cv, max_new_tokens, sample,
                        temperature, top_p, key, use_eos,
                        jnp.asarray(eos_id), jnp.asarray(pad_id))
