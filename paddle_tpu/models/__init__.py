"""Model zoo: flagship transformer families for the TPU framework.

Reference parity targets:
  - Llama decoder family (reference:
    `test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py`,
    the hybrid-parallel Llama used by the north-star config 4).
  - BERT encoder family (config 3: BERT-base MLM under sharding stage-2).
  - Diffusion UNet (config 5: Predictor inference).
  - Vision models live in `paddle_tpu.vision.models`.
"""

from paddle_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    LlamaRMSNorm,
    LlamaRotaryEmbedding,
    LlamaAttention,
    LlamaMLP,
    LlamaDecoderLayer,
    LlamaModel,
    LlamaForCausalLM,
    LlamaPretrainingCriterion,
)
from paddle_tpu.models import llama_functional  # noqa: F401
from paddle_tpu.models.bert import (  # noqa: F401
    BertConfig, BertForPretraining, BertModel, BertPretrainingLoss,
    bert_base, bert_tiny,
)
from paddle_tpu.models.unet import UNetModel, unet_sd_like, unet_tiny  # noqa: F401
from paddle_tpu.models.gpt import (  # noqa: F401
    GPTConfig, GPTForCausalLM, GPTModel, GPTPretrainingLoss,
    gpt_pipeline_descs, gpt_tiny,
)
