"""Llama decoder-only transformer, eager nn.Layer form.

Behavioral reference: the hybrid-parallel Llama the reference trains for its
north-star config (`test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py`;
TP layers `python/paddle/distributed/fleet/layers/mpu/mp_layers.py:49,336,543`).

TPU-native design decisions:
  - attention runs through `nn.functional.flash_attention` which dispatches to
    a Pallas kernel on TPU (reference's `flash_attn_kernel.cu` counterpart);
  - weights are stored [in, out] so matmuls hit the MXU without transposes;
  - tensor parallelism: when fleet is initialised with mp>1 the q/k/v/o and
    MLP projections become Column/RowParallelLinear — sharded over the 'mp'
    mesh axis, with XLA inserting the collectives (GSPMD) instead of the
    reference's hand-written _mp_allreduce (`mp_ops.py:259`);
  - RoPE is applied in float32 for numerical parity with the reference's
    fused rope kernel (`paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu`).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor, apply
from paddle_tpu.nn import functional as F
import importlib

try:  # private API; if it moves, conservatively assume "always tracing"
    from jax._src.core import trace_state_clean as _trace_state_clean
except Exception:  # pragma: no cover - jax upgrade path
    def _trace_state_clean():
        return False  # never cache device tables (recompute is safe)

flash_attn_mod = importlib.import_module("paddle_tpu.nn.functional.flash_attention")

__all__ = [
    "LlamaConfig",
    "LlamaRMSNorm",
    "LlamaRotaryEmbedding",
    "LlamaAttention",
    "LlamaMLP",
    "LlamaDecoderLayer",
    "LlamaModel",
    "LlamaForCausalLM",
    "LlamaPretrainingCriterion",
]


class LlamaConfig:
    """Hyperparameters (mirrors the reference test model's config surface)."""

    def __init__(
        self,
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=11008,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=None,
        max_position_embeddings=4096,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        use_flash_attention=True,
        sequence_parallel=False,
        recompute=False,
        tie_word_embeddings=False,
        dtype="float32",
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = num_key_value_heads or num_attention_heads
        self.max_position_embeddings = max_position_embeddings
        self.rms_norm_eps = rms_norm_eps
        self.rope_theta = rope_theta
        self.use_flash_attention = use_flash_attention
        self.sequence_parallel = sequence_parallel
        self.recompute = recompute
        self.tie_word_embeddings = tie_word_embeddings
        self.dtype = dtype

    @staticmethod
    def llama2_7b(**kw):
        return LlamaConfig(
            vocab_size=32000, hidden_size=4096, intermediate_size=11008,
            num_hidden_layers=32, num_attention_heads=32, **kw)

    @staticmethod
    def tiny(**kw):
        """Small config for tests/benchmarks."""
        kw.setdefault("vocab_size", 512)
        kw.setdefault("hidden_size", 128)
        kw.setdefault("intermediate_size", 352)
        kw.setdefault("num_hidden_layers", 4)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("max_position_embeddings", 256)
        return LlamaConfig(**kw)


def _mp_enabled():
    from paddle_tpu.distributed import fleet

    hcg = fleet.get_hybrid_communicate_group()
    return hcg is not None and hcg.get_model_parallel_world_size() > 1


class LlamaRMSNorm(nn.Layer):
    """RMS norm in fp32 accumulation (reference model's fused_rms_norm path)."""

    def __init__(self, hidden_size, eps=1e-6):
        super().__init__()
        self.weight = self.create_parameter(
            [hidden_size], default_initializer=nn.initializer.Constant(1.0))
        self.variance_epsilon = eps

    def forward(self, x):
        eps = self.variance_epsilon

        def fn(h, w):
            dt = h.dtype
            h32 = h.astype(jnp.float32)
            var = jnp.mean(h32 * h32, axis=-1, keepdims=True)
            return (h32 * (1.0 / jnp.sqrt(var + eps))).astype(dt) * w

        return apply(fn, x, self.weight, _name="rms_norm")


# single source of truth for RoPE math: the functional core
from paddle_tpu.models.llama_functional import (
    apply_rope as _apply_rope, rope_tables as _rope_tables)


class LlamaRotaryEmbedding(nn.Layer):
    def __init__(self, head_dim, max_position_embeddings=4096, theta=10000.0):
        super().__init__()
        self.head_dim = head_dim
        self.max_position_embeddings = max_position_embeddings
        self.theta = theta
        self._cache = {}  # seq_len -> (cos Tensor, sin Tensor), float32

    def forward(self, seq_len):
        if not _trace_state_clean():
            # under jit/export tracing: recompute (XLA folds/fuses the
            # tables). Caching here would close later traces over a large
            # device-array constant, which export lifts into an extra
            # argument and breaks the saved program's input tree.
            cos, sin = _rope_tables(seq_len, self.head_dim, self.theta)
            return Tensor(cos), Tensor(sin)
        if seq_len not in self._cache:
            cos, sin = _rope_tables(seq_len, self.head_dim, self.theta)
            self._cache[seq_len] = (Tensor(cos), Tensor(sin))
        return self._cache[seq_len]


class LlamaAttention(nn.Layer):
    """Multi-head (optionally grouped-query) causal self-attention with RoPE."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.hidden_size = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads

        if _mp_enabled():
            from paddle_tpu.distributed.fleet.layers.mpu.mp_layers import (
                ColumnParallelLinear, RowParallelLinear)

            mk = lambda i, o: ColumnParallelLinear(i, o, has_bias=False,
                                                   gather_output=False)
            self.q_proj = mk(self.hidden_size, self.num_heads * self.head_dim)
            self.k_proj = mk(self.hidden_size, self.num_kv_heads * self.head_dim)
            self.v_proj = mk(self.hidden_size, self.num_kv_heads * self.head_dim)
            self.o_proj = RowParallelLinear(
                self.num_heads * self.head_dim, self.hidden_size,
                has_bias=False, input_is_parallel=True)
        else:
            self.q_proj = nn.Linear(self.hidden_size, self.num_heads * self.head_dim,
                                    bias_attr=False)
            self.k_proj = nn.Linear(self.hidden_size, self.num_kv_heads * self.head_dim,
                                    bias_attr=False)
            self.v_proj = nn.Linear(self.hidden_size, self.num_kv_heads * self.head_dim,
                                    bias_attr=False)
            self.o_proj = nn.Linear(self.num_heads * self.head_dim, self.hidden_size,
                                    bias_attr=False)
        self.rotary_emb = LlamaRotaryEmbedding(
            self.head_dim, config.max_position_embeddings, config.rope_theta)

    def forward(self, hidden_states, attention_mask=None, position_ids=None,
                past_key_value=None, use_cache=False):
        b, s = hidden_states.shape[0], hidden_states.shape[1]
        q = self.q_proj(hidden_states)
        k = self.k_proj(hidden_states)
        v = self.v_proj(hidden_states)

        q = paddle.reshape(q, [b, s, self.num_heads, self.head_dim])
        k = paddle.reshape(k, [b, s, self.num_kv_heads, self.head_dim])
        v = paddle.reshape(v, [b, s, self.num_kv_heads, self.head_dim])

        offset = 0
        if past_key_value is not None:
            offset = past_key_value[0].shape[1]
        cos_t, sin_t = self.rotary_emb(offset + s)  # cached tables

        def rope_fn(qd, kd, cos, sin):
            return _apply_rope(qd, kd, cos[offset:], sin[offset:])

        q, k = apply(rope_fn, q, k, cos_t, sin_t, _name="fused_rope")

        if past_key_value is not None:
            k = paddle.concat([past_key_value[0], k], axis=1)
            v = paddle.concat([past_key_value[1], v], axis=1)
        new_cache = (k, v) if use_cache else None

        # GQA (num_kv_heads < num_heads) passes through natively: the Pallas
        # kernel maps query head h onto kv head h // group, and the XLA
        # fallback repeats kv heads internally — kv is never materialized at
        # full head count here, preserving the KV-cache memory win.

        # causal always holds; with a KV cache the offset diagonal
        # tril(k=sk-sq) lets the query chunk at positions [offset, offset+s)
        # see all cached keys while staying causal within the chunk
        if self.config.use_flash_attention and attention_mask is None:
            out = flash_attn_mod.flash_attention(q, k, v, causal=True)[0]
        else:
            # causality is kept even with a user mask (the reference folds the
            # padding mask into the causal mask before attention)
            out = flash_attn_mod.scaled_dot_product_attention(
                q, k, v, attn_mask=attention_mask, is_causal=True)
        out = paddle.reshape(out, [b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if use_cache:
            return out, new_cache
        return out


class LlamaMLP(nn.Layer):
    """SwiGLU MLP: down(silu(gate(x)) * up(x))."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        if _mp_enabled():
            from paddle_tpu.distributed.fleet.layers.mpu.mp_layers import (
                ColumnParallelLinear, RowParallelLinear)

            self.gate_proj = ColumnParallelLinear(h, i, has_bias=False,
                                                  gather_output=False)
            self.up_proj = ColumnParallelLinear(h, i, has_bias=False,
                                                gather_output=False)
            self.down_proj = RowParallelLinear(i, h, has_bias=False,
                                               input_is_parallel=True)
        else:
            self.gate_proj = nn.Linear(h, i, bias_attr=False)
            self.up_proj = nn.Linear(h, i, bias_attr=False)
            self.down_proj = nn.Linear(i, h, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = LlamaRMSNorm(config.hidden_size, config.rms_norm_eps)
        self.post_attention_layernorm = LlamaRMSNorm(config.hidden_size,
                                                     config.rms_norm_eps)
        self.config = config

    def forward(self, hidden_states, attention_mask=None, position_ids=None,
                past_key_value=None, use_cache=False):
        def block(h):
            residual = h
            h = self.input_layernorm(h)
            h = self.self_attn(h, attention_mask, position_ids)
            h = residual + h
            residual = h
            h = self.post_attention_layernorm(h)
            h = self.mlp(h)
            return residual + h

        if use_cache:
            residual = hidden_states
            h = self.input_layernorm(hidden_states)
            h, cache = self.self_attn(h, attention_mask, position_ids,
                                      past_key_value, use_cache=True)
            h = residual + h
            residual = h
            h = self.post_attention_layernorm(h)
            h = self.mlp(h)
            return residual + h, cache

        if self.config.recompute:
            from paddle_tpu.distributed.fleet.recompute import recompute

            return recompute(block, hidden_states)
        return block(hidden_states)


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        if _mp_enabled():
            from paddle_tpu.distributed.fleet.layers.mpu.mp_layers import (
                VocabParallelEmbedding)

            self.embed_tokens = VocabParallelEmbedding(
                config.vocab_size, config.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = LlamaRMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, attention_mask=None, position_ids=None,
                past_key_values=None, use_cache=False):
        h = self.embed_tokens(input_ids)
        caches = [] if use_cache else None
        for i, layer in enumerate(self.layers):
            pkv = past_key_values[i] if past_key_values is not None else None
            if use_cache:
                h, cache = layer(h, attention_mask, position_ids, pkv, use_cache=True)
                caches.append(cache)
            else:
                h = layer(h, attention_mask, position_ids)
        h = self.norm(h)
        if use_cache:
            return h, caches
        return h


class LlamaPretrainingCriterion(nn.Layer):
    """Shifted next-token cross entropy (the reference uses
    ParallelCrossEntropy under mp; GSPMD handles the vocab-sharded logits)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config

    def forward(self, logits, labels):
        # logits: [b, s, vocab]; labels: [b, s]
        loss = F.cross_entropy(
            paddle.reshape(logits, [-1, logits.shape[-1]]),
            paddle.reshape(labels, [-1]),
            reduction="mean")
        return loss


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = self.model = LlamaModel(config)
        if _mp_enabled():
            from paddle_tpu.distributed.fleet.layers.mpu.mp_layers import (
                ColumnParallelLinear)

            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, labels=None, attention_mask=None,
                position_ids=None, past_key_values=None, use_cache=False):
        if use_cache:
            h, caches = self.model(input_ids, attention_mask, position_ids,
                                   past_key_values, use_cache=True)
            return self.lm_head(h), caches
        h = self.model(input_ids, attention_mask, position_ids)
        logits = self.lm_head(h)
        if labels is not None:
            return LlamaPretrainingCriterion(self.config)(logits, labels)
        return logits

    @paddle.no_grad()
    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 compiled=False, top_p=1.0, seed=0):
        """Greedy/temperature decode with KV cache.

        compiled=True runs the whole decode as ONE jitted program with a
        fixed-size cache (models/generation.py) — the TPU serving path; the
        default eager loop re-dispatches per step (debuggable, any shape).
        Greedy outputs are parity-tested identical between the two."""
        if compiled:
            import jax

            from paddle_tpu.models import generation as gen
            from paddle_tpu.models import llama_functional as lf

            params = gen.params_from_layer(self)
            args = lf.LlamaArgs.from_config(self.config)
            ids = input_ids.numpy() if hasattr(input_ids, "numpy") \
                else input_ids
            out = gen.generate(params, args, ids,
                               max_new_tokens=max_new_tokens,
                               temperature=temperature, top_p=top_p,
                               key=jax.random.key(seed))
            return paddle.to_tensor(out)
        if seed:
            import warnings

            warnings.warn("generate(seed=...) is only honored on the "
                          "compiled path; the eager loop draws from the "
                          "global generator (use paddle.seed)")
        tokens = input_ids
        past = None
        cur = tokens
        for _ in range(max_new_tokens):
            logits, past = self.forward(cur, past_key_values=past, use_cache=True)
            next_logits = logits[:, -1, :]
            if temperature and temperature > 0:
                next_logits = next_logits / temperature
                if top_p < 1.0:
                    # nucleus mask, same rule as the compiled sampler
                    sorted_l = paddle.sort(next_logits, axis=-1,
                                           descending=True)
                    probs_s = F.softmax(sorted_l, axis=-1)
                    cum = paddle.cumsum(probs_s, axis=-1)
                    k = paddle.sum(paddle.cast(cum < top_p, "int32"),
                                   axis=-1, keepdim=True)
                    cutoff = paddle.take_along_axis(sorted_l, k, axis=-1)
                    next_logits = paddle.where(
                        next_logits >= cutoff, next_logits,
                        paddle.full_like(next_logits, -1e30))
                probs = F.softmax(next_logits, axis=-1)
                nxt = paddle.multinomial(probs, 1)
            else:
                nxt = paddle.argmax(next_logits, axis=-1, keepdim=True)
            nxt = paddle.cast(nxt, tokens.dtype)
            tokens = paddle.concat([tokens, nxt], axis=1)
            cur = nxt
        return tokens
