"""Initializers (reference: `python/paddle/nn/initializer/`)."""

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework import dtypes, random as _rng


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError

    def _compute_fans(self, shape):
        if len(shape) == 0:
            return 1, 1
        if len(shape) == 1:
            return shape[0], shape[0]
        if len(shape) == 2:
            return shape[0], shape[1]
        receptive = int(np.prod(shape[2:]))
        return shape[1] * receptive, shape[0] * receptive


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtypes.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        dt = dtypes.convert_dtype(dtype)
        return self.mean + self.std * jax.random.normal(_rng.next_key(), tuple(shape), dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        dt = dtypes.convert_dtype(dtype)
        lo = (self.a - self.mean) / self.std
        hi = (self.b - self.mean) / self.std
        out = jax.random.truncated_normal(_rng.next_key(), lo, hi, tuple(shape), jnp.float32)
        return (self.mean + self.std * out).astype(dt)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        dt = dtypes.convert_dtype(dtype)
        return jax.random.uniform(_rng.next_key(), tuple(shape), dt, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fin, fout = self._compute_fans(shape)
        fin = self.fan_in or fin
        fout = self.fan_out or fout
        std = self.gain * math.sqrt(2.0 / (fin + fout))
        dt = dtypes.convert_dtype(dtype)
        return std * jax.random.normal(_rng.next_key(), tuple(shape), dt)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fin, fout = self._compute_fans(shape)
        fin = self.fan_in or fin
        fout = self.fan_out or fout
        limit = self.gain * math.sqrt(6.0 / (fin + fout))
        dt = dtypes.convert_dtype(dtype)
        return jax.random.uniform(_rng.next_key(), tuple(shape), dt, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fin, _ = self._compute_fans(shape)
        fin = self.fan_in or fin
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fin)
        dt = dtypes.convert_dtype(dtype)
        return std * jax.random.normal(_rng.next_key(), tuple(shape), dt)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fin, _ = self._compute_fans(shape)
        fin = self.fan_in or fin
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fin)
        dt = dtypes.convert_dtype(dtype)
        return jax.random.uniform(_rng.next_key(), tuple(shape), dt, -limit, limit)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(_rng.next_key(), (max(rows, cols), min(rows, cols)))
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diag(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtypes.convert_dtype(dtype))


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        from paddle_tpu.core.tensor import Tensor

        v = self.value
        arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
        return arr.reshape(tuple(shape)).astype(dtypes.convert_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(min(oc // self.groups, ic)):
                idx = (g * (oc // self.groups) + i, i) + tuple(centers)
                out[idx] = 1.0
        return jnp.asarray(out).astype(dtypes.convert_dtype(dtype))


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = param if param is not None else 0.01
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


def set_global_initializer(weight_init, bias_init=None):
    # stored for create_parameter default lookup
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


_global_weight_init = None
_global_bias_init = None


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (reference
    `nn/initializer/Bilinear`): weight[c_out, c_in, kh, kw] filled with
    the separable triangle filter so a stride-s deconv starts as exact
    bilinear interpolation."""

    def __call__(self, shape, dtype):
        if len(shape) != 4:
            raise ValueError(
                "Bilinear initializer expects a 4-D conv weight, got "
                f"shape {list(shape)}")
        kh, kw = shape[2], shape[3]

        def tri(k):
            f = (k + 1) // 2
            center = f - 1 if k % 2 == 1 else f - 0.5
            return 1 - np.abs(np.arange(k) - center) / f

        kernel = np.outer(tri(kh), tri(kw)).astype("float32")
        w = np.zeros(tuple(shape), "float32")
        for i in range(shape[0]):
            w[i, i % shape[1]] = kernel
        return jnp.asarray(w, dtypes.convert_dtype(dtype))
