"""paddle.nn surface (reference: `python/paddle/nn/__init__.py`)."""

from paddle_tpu.nn.layer.layers import Layer, Parameter, ParamAttr  # noqa: F401
from paddle_tpu.nn.layer.common import (  # noqa: F401
    Identity, Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    Flatten, Upsample, UpsamplingNearest2D, UpsamplingBilinear2D,
    Pad1D, Pad2D, Pad3D, ZeroPad2D, CosineSimilarity, Bilinear,
    PixelShuffle, PixelUnshuffle, ChannelShuffle, Unfold, Fold,
)
from paddle_tpu.nn.layer.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from paddle_tpu.nn.layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
)
from paddle_tpu.nn.layer.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
)
from paddle_tpu.nn.layer.activation import (  # noqa: F401
    ReLU, ReLU6, Sigmoid, Tanh, GELU, SiLU, Swish, Mish, LeakyReLU, ELU, SELU, CELU,
    Hardtanh, Hardshrink, Softshrink, Tanhshrink, Hardsigmoid, Hardswish,
    Softplus, Softsign, LogSigmoid, Softmax, LogSoftmax, ThresholdedReLU,
    Maxout, GLU, RReLU, PReLU,
)
from paddle_tpu.nn.layer.container import (  # noqa: F401
    Sequential, LayerList, LayerDict, ParameterList,
)
from paddle_tpu.nn.layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, SmoothL1Loss, HuberLoss, NLLLoss,
    BCELoss, BCEWithLogitsLoss, KLDivLoss, MarginRankingLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, CTCLoss,
)
from paddle_tpu.nn.layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerEncoder, TransformerEncoderLayer,
    TransformerDecoder, TransformerDecoderLayer,
)
from paddle_tpu.nn.layer.rnn import (  # noqa: F401
    SimpleRNN, LSTM, GRU, SimpleRNNCell, LSTMCell, GRUCell,
)
from paddle_tpu.nn.layer.extras import *  # noqa: F401,F403

from paddle_tpu.nn import functional  # noqa: F401
from paddle_tpu.nn import initializer  # noqa: F401
from paddle_tpu.nn import utils  # noqa: F401


class ClipGradByNorm:
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm


class ClipGradByGlobalNorm:
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = clip_norm


class ClipGradByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max
