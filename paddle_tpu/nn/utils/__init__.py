"""nn.utils (reference: `python/paddle/nn/utils/`): clip_grad helpers,
parameters_to_vector, weight_norm, spectral_norm wrappers."""

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g._data), norm_type)) for g in grads),
            1.0 / norm_type)
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for g in grads:
        g._data = g._data * clip_coef.astype(g.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)


def parameters_to_vector(parameters, name=None):
    return Tensor(jnp.concatenate([p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p._data = vec._data[offset:offset + n].reshape(tuple(p.shape)).astype(p.dtype)
        offset += n


def weight_norm(layer, name="weight", dim=0):
    # lightweight reparameterization: store g/v, recompute weight pre-forward
    import numpy as np

    w = getattr(layer, name)
    g = jnp.linalg.norm(w._data.reshape(w.shape[dim] if dim == 0 else -1, -1), axis=1) if dim == 0 \
        else jnp.linalg.norm(w._data, axis=tuple(i for i in range(w.ndim) if i != dim))
    from paddle_tpu.nn.layer.layers import Parameter

    layer.add_parameter(name + "_g", Parameter(g))
    layer.add_parameter(name + "_v", Parameter(w._data))

    def hook(l, inputs):
        v = getattr(l, name + "_v")
        gg = getattr(l, name + "_g")
        axes = tuple(i for i in range(v.ndim) if i != dim)
        norm = jnp.sqrt(jnp.sum(v._data * v._data, axis=axes, keepdims=True) + 1e-12)
        shape = [1] * v.ndim
        shape[dim] = -1
        w_new = v._data / norm * gg._data.reshape(shape)
        getattr(l, name)._data = w_new

    layer.register_forward_pre_hook(hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    for attr in (name + "_g", name + "_v"):
        if attr in layer._parameters:
            del layer._parameters[attr]
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    return layer
