"""Flash attention (reference dispatch: `python/paddle/nn/functional/flash_attention.py:486-530`;
reference kernel: `paddle/phi/kernels/gpu/flash_attn_kernel.cu`).

TPU-native design: Pallas fwd+bwd kernels (`paddle_tpu/kernels/flash_attention.py`)
when running on TPU with supported shapes, otherwise an XLA softmax(QK^T)V
fallback that the compiler fuses. GQA (fewer kv heads than query heads) is
native in the Pallas path; the fallback repeats kv heads. Attention dropout
runs in the fallback path (the Pallas kernels are deterministic, so dropout>0
in training routes to the fallback). Layout is paddle's
[batch, seqlen, nheads, headdim].
"""

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, apply
from paddle_tpu.framework import random as _rng


def _sdpa_reference(q, k, v, causal=False, dropout=0.0, scale=None, mask=None,
                    dropout_key=None):
    # q: [B, L, H, D]; k/v: [B, Lk, Hk, D] -> compute in [B, H, L, D]
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    if mask is not None:
        if mask.dtype == jnp.bool_:
            # paddle bool-mask semantics: False = masked out
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(causal_mask, logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if dropout > 0.0 and dropout_key is not None:
        # attention-probability dropout (reference applies dropout to the
        # softmax output before the value matmul, flash_attn_kernel.cu)
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
    probs = probs.astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def _use_pallas(q, k, dropout=0.0, training=True, mask=None):
    if jax.default_backend() != "tpu":
        return False
    if mask is not None:
        return False
    if dropout > 0.0 and training:
        # the Pallas kernels are deterministic; dropout runs in the fallback
        return False
    from paddle_tpu.kernels import flash_attention as fa

    return fa.supports(q.shape, k.shape, q.dtype.itemsize)


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    drop = dropout if training else 0.0
    dropout_key = _rng.next_key() if drop > 0.0 else None

    def fn(q, k, v):
        if _use_pallas(q, k, dropout=drop, training=training):
            from paddle_tpu.kernels.flash_attention import flash_attention_fwd

            return flash_attention_fwd(q, k, v, causal=causal)
        return _sdpa_reference(q, k, v, causal=causal, dropout=drop,
                               dropout_key=dropout_key)

    out = apply(fn, query, key, value, _name="flash_attention")
    return out, None


def flash_attn_unpadded(*args, **kwargs):
    raise NotImplementedError("varlen flash attention: use dense + mask on TPU")


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    m = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
    drop = dropout_p if training else 0.0
    dropout_key = _rng.next_key() if drop > 0.0 else None

    def fn(q, k, v):
        if _use_pallas(q, k, dropout=drop, training=training, mask=m):
            from paddle_tpu.kernels.flash_attention import flash_attention_fwd

            return flash_attention_fwd(q, k, v, causal=is_causal)
        return _sdpa_reference(q, k, v, causal=is_causal, mask=m, dropout=drop,
                               dropout_key=dropout_key)

    return apply(fn, query, key, value, _name="sdpa")


def sdp_kernel(*args, **kwargs):
    import contextlib

    return contextlib.nullcontext()
