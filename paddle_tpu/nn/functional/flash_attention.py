"""Flash attention (reference dispatch: `python/paddle/nn/functional/flash_attention.py:486-530`;
reference kernel: `paddle/phi/kernels/gpu/flash_attn_kernel.cu`).

TPU-native design: Pallas fwd+bwd kernels (`paddle_tpu/kernels/flash_attention.py`)
when running on TPU with supported shapes, otherwise an XLA softmax(QK^T)V
fallback that the compiler fuses. GQA (fewer kv heads than query heads) is
native in the Pallas path; the fallback repeats kv heads. Attention dropout
runs in the fallback path (the Pallas kernels are deterministic, so dropout>0
in training routes to the fallback). Layout is paddle's
[batch, seqlen, nheads, headdim].
"""

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, apply
from paddle_tpu.framework import random as _rng


def _sdpa_reference(q, k, v, causal=False, dropout=0.0, scale=None, mask=None,
                    dropout_key=None):
    # q: [B, L, H, D]; k/v: [B, Lk, Hk, D] -> compute in [B, H, L, D]
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    if mask is not None:
        if mask.dtype == jnp.bool_:
            # paddle bool-mask semantics: False = masked out
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(causal_mask, logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if dropout > 0.0 and dropout_key is not None:
        # attention-probability dropout (reference applies dropout to the
        # softmax output before the value matmul, flash_attn_kernel.cu)
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
    probs = probs.astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def _use_pallas(q, k, dropout=0.0, training=True, mask=None):
    if jax.default_backend() != "tpu":
        return False
    if mask is not None:
        return False
    if dropout > 0.0 and training:
        # the Pallas kernels are deterministic; dropout runs in the fallback
        return False
    from paddle_tpu.kernels import flash_attention as fa

    return fa.supports(q.shape, k.shape, q.dtype.itemsize)


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    drop = dropout if training else 0.0
    if drop > 0.0:
        # key rides the waist (SOT marks it refresh-on-replay)
        key_t = _rng.next_key_tensor()

        def fn_d(q, k, v, dkey):
            return _sdpa_reference(q, k, v, causal=causal, dropout=drop,
                                   dropout_key=dkey)

        out = apply(fn_d, query, key, value, key_t, _name="flash_attention")
        return out, None

    def fn(q, k, v):
        if _use_pallas(q, k, dropout=drop, training=training):
            from paddle_tpu.kernels.flash_attention import flash_attention_fwd

            return flash_attention_fwd(q, k, v, causal=causal)
        return _sdpa_reference(q, k, v, causal=causal, dropout=drop)

    out = apply(fn, query, key, value, _name="flash_attention")
    return out, None


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """Packed-QKV flash attention (reference
    `nn/functional/flash_attention.py` flash_attn_qkvpacked): qkv is
    [b, s, 3, h, d]; unpack and run the same kernel."""
    from paddle_tpu.ops.manipulation import squeeze, split

    q, k, v = split(qkv, 3, axis=2)
    q, k, v = (squeeze(t, axis=2) for t in (q, k, v))
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen flash attention (reference flash_attn_unpadded): tokens of
    all sequences packed along dim 0 with cu_seqlens boundaries. TPU path:
    re-pad to [nseq, max_seqlen] dense batches with a validity mask (XLA
    wants static shapes; the padded FLOPs are masked out of the result),
    run masked SDPA, then re-pack. Routed through apply() so autograd
    flows into q/k/v. Eager-only (data-dependent shapes)."""
    import numpy as np

    cq = np.asarray(cu_seqlens_q.numpy()
                    if isinstance(cu_seqlens_q, Tensor) else cu_seqlens_q)
    ck = np.asarray(cu_seqlens_k.numpy()
                    if isinstance(cu_seqlens_k, Tensor) else cu_seqlens_k)
    nseq = len(cq) - 1
    mq, mk = int(max_seqlen_q), int(max_seqlen_k)
    drop = dropout if training else 0.0
    key_t = _rng.next_key_tensor() if drop > 0.0 else None

    def fn(qa, ka, va, *maybe_key):
        def pad_batch(a, cu, m):
            h, d = a.shape[1], a.shape[2]
            out = jnp.zeros((nseq, m, h, d), a.dtype)
            for i in range(nseq):
                ln = int(cu[i + 1] - cu[i])
                out = out.at[i, :ln].set(a[int(cu[i]):int(cu[i + 1])])
            return out

        qb = pad_batch(qa, cq, mq)
        kb = pad_batch(ka, ck, mk)
        vb = pad_batch(va, ck, mk)
        klens = jnp.asarray(ck[1:] - ck[:-1])
        kmask = (jnp.arange(mk)[None, :] < klens[:, None])
        bias = jnp.where(kmask, 0.0, -jnp.inf)[:, None, None, :]
        out = _sdpa_reference(qb, kb, vb, causal=causal, mask=bias,
                              dropout=drop, scale=scale,
                              dropout_key=maybe_key[0] if maybe_key else None)
        return jnp.concatenate(
            [out[i, :int(cq[i + 1] - cq[i])] for i in range(nseq)], axis=0)

    extra = (key_t,) if key_t is not None else ()
    out = apply(fn, query, key, value, *extra, _name="flash_attn_unpadded")
    return out, None


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale=None,
                                dropout=0.0, causal=False,
                                return_softmax=False, fixed_seed_offset=None,
                                rng_name="", training=True, varlen_padded=True,
                                name=None):
    """Varlen packed-QKV (reference flash_attn_varlen_qkvpacked):
    qkv [total_tokens, 3, h, d] -> unpack (grad-preserving slices) +
    unpadded path."""
    q = qkv[:, 0]
    k = qkv[:, 1]
    v = qkv[:, 2]
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale=scale,
                               dropout=dropout, causal=causal,
                               return_softmax=return_softmax,
                               training=training)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    m = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
    drop = dropout_p if training else 0.0
    if drop > 0.0:
        key_t = _rng.next_key_tensor()

        def fn_d(q, k, v, dkey):
            return _sdpa_reference(q, k, v, causal=is_causal, mask=m,
                                   dropout=drop, dropout_key=dkey)

        return apply(fn_d, query, key, value, key_t, _name="sdpa")

    def fn(q, k, v):
        if _use_pallas(q, k, dropout=drop, training=training, mask=m):
            from paddle_tpu.kernels.flash_attention import flash_attention_fwd

            return flash_attention_fwd(q, k, v, causal=is_causal)
        return _sdpa_reference(q, k, v, causal=is_causal, mask=m, dropout=drop)

    return apply(fn, query, key, value, _name="sdpa")


def sdp_kernel(*args, **kwargs):
    import contextlib

    return contextlib.nullcontext()
