"""Flash attention (reference dispatch: `python/paddle/nn/functional/flash_attention.py:486-530`;
reference kernel: `paddle/phi/kernels/gpu/flash_attn_kernel.cu`).

TPU-native design: a Pallas splash-style kernel (`paddle_tpu/kernels/flash_attention.py`)
when running on TPU, otherwise an XLA softmax(QK^T)V fallback that the compiler
fuses. Layout is paddle's [batch, seqlen, nheads, headdim].
"""

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, apply


def _sdpa_reference(q, k, v, causal=False, dropout=0.0, scale=None, mask=None):
    # q/k/v: [B, L, H, D] -> compute in [B, H, L, D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    if mask is not None:
        if mask.dtype == jnp.bool_:
            # paddle bool-mask semantics: False = masked out
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(causal_mask, logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def _use_pallas(q):
    return jax.default_backend() == "tpu" and q.shape[1] % 128 == 0


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    def fn(q, k, v):
        if _use_pallas(q):
            try:
                from paddle_tpu.kernels.flash_attention import flash_attention_fwd

                return flash_attention_fwd(q, k, v, causal=causal)
            except Exception:
                pass
        return _sdpa_reference(q, k, v, causal=causal)

    out = apply(fn, query, key, value, _name="flash_attention")
    return out, None


def flash_attn_unpadded(*args, **kwargs):
    raise NotImplementedError("varlen flash attention: use dense + mask on TPU")


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    m = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask

    def fn(q, k, v):
        if m is None and _use_pallas(q):
            try:
                from paddle_tpu.kernels.flash_attention import flash_attention_fwd

                return flash_attention_fwd(q, k, v, causal=is_causal)
            except Exception:
                pass
        return _sdpa_reference(q, k, v, causal=is_causal, mask=m)

    return apply(fn, query, key, value, _name="sdpa")


def sdp_kernel(*args, **kwargs):
    import contextlib

    return contextlib.nullcontext()
