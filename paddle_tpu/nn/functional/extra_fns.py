"""nn.functional completion (r5 surface sweep): the reference
`python/paddle/nn/functional/__init__.py` members not covered elsewhere —
losses, pooling variants, in-place activations, attention variants.
Reference implementations: `python/paddle/nn/functional/{loss,pooling,
activation,flash_attention}.py`."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor, apply

__all__ = [
    "pairwise_distance", "poisson_nll_loss", "gaussian_nll_loss",
    "soft_margin_loss", "multi_label_soft_margin_loss",
    "multi_margin_loss", "triplet_margin_with_distance_loss",
    "adaptive_log_softmax_with_loss", "feature_alpha_dropout",
    "lp_pool1d", "elu_", "hardtanh_", "leaky_relu_", "tanh_",
    "thresholded_relu_", "class_center_sample", "flashmask_attention",
    "sparse_attention",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """reference F.pairwise_distance: ||x - y + eps||_p along the last
    dim."""
    return apply(
        lambda a, b: jnp.linalg.norm(a - b + epsilon, ord=p, axis=-1,
                                     keepdims=keepdim),
        x, y, _name="pairwise_distance")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def fn(inp, lab):
        if log_input:
            loss = jnp.exp(inp) - lab * inp
        else:
            loss = inp - lab * jnp.log(inp + epsilon)
        if full:
            # Stirling approximation of log(label!)
            stir = (lab * jnp.log(lab) - lab
                    + 0.5 * jnp.log(2 * math.pi * lab))
            loss = loss + jnp.where(lab > 1, stir, 0.0)
        return _reduce(loss, reduction)

    return apply(fn, input, label, _name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def fn(mu, lab, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (lab - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * math.log(2 * math.pi)
        return _reduce(loss, reduction)

    return apply(fn, input, label, variance, _name="gaussian_nll_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    return apply(
        lambda a, t: _reduce(jnp.log1p(jnp.exp(-t * a)), reduction),
        input, label, _name="soft_margin_loss")


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    def fn(a, t, *w):
        loss = -(t * jax.nn.log_sigmoid(a)
                 + (1 - t) * jax.nn.log_sigmoid(-a))
        if w:
            loss = loss * w[0]
        return _reduce(loss.mean(axis=-1), reduction)

    args = [weight] if weight is not None else []
    return apply(fn, input, label, *args,
                 _name="multi_label_soft_margin_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    def fn(a, t, *w):
        t = t.astype(jnp.int32)
        true_score = jnp.take_along_axis(a, t[:, None], axis=1)
        diff = jnp.maximum(margin - true_score + a, 0.0) ** p
        if w:
            diff = diff * jnp.take(w[0], t)[:, None]
        C = a.shape[1]
        mask = jax.nn.one_hot(t, C) == 0
        loss = jnp.where(mask, diff, 0.0).sum(axis=1) / C
        return _reduce(loss, reduction)

    args = [weight] if weight is not None else []
    return apply(fn, input, label, *args, _name="multi_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    dist = distance_function or (
        lambda a, b: pairwise_distance(a, b))
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn2 = dist(positive, negative)
        from paddle_tpu.ops.math import minimum

        dn = minimum(dn, dn2)
    out = apply(lambda p_, n_: jnp.maximum(p_ - n_ + margin, 0.0),
                dp, dn, _name="triplet_margin_with_distance")
    return apply(lambda o: _reduce(o, reduction), out, _name="reduce")


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (reference F.adaptive_log_softmax_with_loss;
    Grave et al. 2017): frequent classes in the head, rare classes in
    down-projected tail clusters. Returns (per-sample output logprob,
    scalar mean loss)."""
    # cutoffs includes the total class count: clusters are
    # [cutoffs[i], cutoffs[i+1]) and the head has shortlist + n_clusters
    # columns (one routing logit per cluster)
    shortlist = cutoffs[0]
    bounds = list(zip(cutoffs[:-1], cutoffs[1:]))
    has_bias = head_bias is not None
    flat_tails = [w for pair in tail_weights for w in pair]

    def fn(x, lab, hw, *rest):
        lab = lab.astype(jnp.int32)
        hb = rest[0] if has_bias else None
        tails = rest[1 if has_bias else 0:]
        head_logits = x @ hw + (hb if hb is not None else 0.0)
        head_lp = jax.nn.log_softmax(head_logits, axis=-1)
        in_short = lab < shortlist
        out = jnp.take_along_axis(
            head_lp, jnp.clip(lab, 0, shortlist - 1)[:, None], axis=1)[:, 0]
        out = jnp.where(in_short, out, 0.0)
        for ci, (lo, hi) in enumerate(bounds):
            w1, w2 = tails[2 * ci], tails[2 * ci + 1]
            tail_lp = jax.nn.log_softmax((x @ w1) @ w2, axis=-1)
            in_c = (lab >= lo) & (lab < hi)
            idx = jnp.clip(lab - lo, 0, tail_lp.shape[1] - 1)
            lp = head_lp[:, shortlist + ci] + jnp.take_along_axis(
                tail_lp, idx[:, None], axis=1)[:, 0]
            out = jnp.where(in_c, lp, out)
        return out, -out.mean()

    args = [input, label, head_weight]
    if has_bias:
        args.append(head_bias)
    return apply(fn, *args, *flat_tails,
                 _name="adaptive_log_softmax_with_loss")


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout over whole feature maps (reference
    F.feature_alpha_dropout): SELU-compatible noise applied per channel."""
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    from paddle_tpu.framework import random as _rng

    alpha_p = -1.7580993408473766
    key_t = _rng.next_key_tensor()

    def fn(a, key):
        shape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(key, 1 - p, shape)
        A = (1 - p + p * alpha_p ** 2) ** -0.5
        B = -A * p * alpha_p
        return A * jnp.where(keep, a, alpha_p) + B

    return apply(fn, x, key_t, _name="feature_alpha_dropout")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """reference F.lp_pool1d: power-mean pooling over 1-D windows."""
    def fn(a):
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        s = stride if stride is not None else k
        s = s if isinstance(s, int) else s[0]
        powed = jnp.abs(a) ** norm_type
        summed = jax.lax.reduce_window(
            powed, 0.0, jax.lax.add, (1, 1, k), (1, 1, s),
            [(0, 0), (0, 0), (padding, padding)])
        return summed ** (1.0 / norm_type)

    return apply(fn, x, _name="lp_pool1d")


def _inplace_act(fn_name):
    from paddle_tpu.core.ops_patch import make_inplace
    from paddle_tpu.nn.functional import activation as _act

    op_ = make_inplace(getattr(_act, fn_name))
    op_.__name__ = fn_name + "_"
    return op_


elu_ = _inplace_act("elu")
hardtanh_ = _inplace_act("hardtanh")
leaky_relu_ = _inplace_act("leaky_relu")
tanh_ = _inplace_act("tanh")
thresholded_relu_ = _inplace_act("thresholded_relu")


def class_center_sample(label, num_classes, num_samples, group=None):
    from paddle_tpu.ops.legacy_ps import class_center_sample as _ccs

    return _ccs(label, num_classes, num_samples)


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, window_size=None,
                        return_softmax_lse=False, return_seed_offset=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """FlashMask attention (reference F.flashmask_attention): sparse
    row-interval masks for flash attention. The interval encoding
    (startend_row_indices [B, H?, S, k]) is expanded to a dense mask and
    fed to the standard attention path — same math; the flash-kernel
    interval skipping is an optimization this backend leaves to XLA
    fusion (documented divergence)."""
    from paddle_tpu.nn.functional.flash_attention import (
        scaled_dot_product_attention)

    mask = None
    if startend_row_indices is not None:
        idx = (startend_row_indices._data
               if isinstance(startend_row_indices, Tensor)
               else jnp.asarray(startend_row_indices))
        q = query._data if isinstance(query, Tensor) else query
        S = q.shape[1]
        # idx: [B, H, S, k] with k=1 (lower bound) or 2 (start, end)
        qrow = jnp.arange(S)[None, None, :, None]
        if idx.shape[-1] == 1:
            # one column: start row per key col; masked iff q_row >= start
            st = jnp.swapaxes(idx[..., 0][..., None], -1, -2)
            masked = qrow >= st
        else:
            # (start, end) interval per key col; masked inside [start, end)
            st = jnp.swapaxes(idx[..., 0][..., None], -1, -2)
            en = jnp.swapaxes(idx[..., 1][..., None], -1, -2)
            masked = (qrow >= st) & (qrow < en)
        mask = Tensor(jnp.where(masked, -jnp.inf, 0.0).astype(q.dtype))
    out = scaled_dot_product_attention(
        query, key, value, attn_mask=mask, dropout_p=dropout,
        is_causal=causal, training=training)
    return out


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention with a CSR connectivity pattern (reference
    F.sparse_attention / `phi/kernels/gpu/sparse_attention_kernel`): each
    query row attends only to its CSR columns. Dense-mask formulation —
    the mask zeros the non-connected logits; same math as the kernel."""
    # the CSR pattern is data, not a differentiable operand: concretize
    # it here, BEFORE apply — inside the op fn the inputs are vjp tracers
    # whenever q/k/v require grad, and tracers cannot be read on host
    q0 = query._data if isinstance(query, Tensor) else jnp.asarray(query)
    B, H, S, _ = q0.shape
    off = sparse_csr_offset._data if isinstance(sparse_csr_offset, Tensor) \
        else jnp.asarray(sparse_csr_offset)
    cols = sparse_csr_columns._data \
        if isinstance(sparse_csr_columns, Tensor) \
        else jnp.asarray(sparse_csr_columns)
    offh = np.asarray(jax.device_get(off)).astype(np.int64)
    colh = np.asarray(jax.device_get(cols)).astype(np.int64)
    m = np.full((B, H, S, S), False)
    for b in range(B):
        for h in range(H):
            o = offh[b, h]
            c = colh[b, h]
            for r in range(S):
                m[b, h, r, c[o[r]:o[r + 1]]] = True
    allow = jnp.asarray(m)

    def fn(q, k, v):
        D = q.shape[-1]
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(D)
        logits = jnp.where(allow, logits, -jnp.inf)
        w = jax.nn.softmax(logits, axis=-1)
        w = jnp.where(jnp.isnan(w), 0.0, w)
        return jnp.einsum("bhqk,bhkd->bhqd", w, v)

    return apply(fn, query, key, value, _name="sparse_attention")
