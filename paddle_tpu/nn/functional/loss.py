"""Loss functionals (reference: `python/paddle/nn/functional/loss.py`)."""

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, apply


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    lbl = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def fn(logits, *w):
        lg = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(lg, axis=axis) if use_softmax else jnp.log(jnp.maximum(lg, 1e-30))
        if soft_label:
            tgt = lbl.astype(jnp.float32)
            if label_smoothing > 0:
                k = logits.shape[axis]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=axis)
        else:
            l = lbl
            if l.ndim == logits.ndim and l.shape[axis] == 1:
                l = jnp.squeeze(l, axis)
            valid = l != ignore_index
            l_safe = jnp.where(valid, l, 0)
            picked = jnp.take_along_axis(logp, l_safe[..., None].astype(jnp.int32), axis=axis)[..., 0]
            if label_smoothing > 0:
                k = logits.shape[axis]
                smooth = jnp.mean(logp, axis=axis)
                picked = (1 - label_smoothing) * picked + label_smoothing * smooth
            loss = jnp.where(valid, -picked, 0.0)
            wt = None
            if w:
                wt = jnp.take(w[0], l_safe, axis=0) * valid.astype(loss.dtype)
                loss = loss * wt
            if reduction == "mean":
                # weighted mean divides by the sum of sample weights
                # (reference semantics, `python/paddle/nn/functional/loss.py`)
                denom = jnp.sum(wt) if wt is not None else jnp.sum(valid.astype(jnp.float32))
                return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
        return _reduce(loss, reduction)

    args = [weight] if weight is not None else []
    return apply(fn, input, *args, _name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)
    from paddle_tpu.ops.manipulation import unsqueeze

    loss = unsqueeze(loss, axis)
    if return_softmax:
        from paddle_tpu.nn.functional.activation import softmax as _sm

        return loss, _sm(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    lbl = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def fn(logp, *w):
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(logp, safe[..., None].astype(jnp.int32), axis=1)[..., 0] if logp.ndim == 2 \
            else jnp.take_along_axis(logp, safe[:, None].astype(jnp.int32), axis=1)[:, 0]
        loss = jnp.where(valid, -picked, 0.0)
        wt = None
        if w:
            wt = jnp.take(w[0], safe, axis=0) * valid.astype(loss.dtype)
            loss = loss * wt
        if reduction == "mean":
            denom = jnp.sum(wt) if wt is not None else jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [weight] if weight is not None else []
    return apply(fn, input, *args, _name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction), input, label, _name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label, _name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply(fn, input, label, _name="smooth_l1")


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply(fn, input, label, _name="huber_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, t, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-7)
        loss = -(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    args = [weight] if weight is not None else []
    return apply(fn, input, label, *args, _name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def fn(z, t, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]
            i += 1
        if pos_weight is not None:
            pw = rest[i]
        neg_abs = -jnp.abs(z)
        if pw is not None:
            log_weight = (pw - 1) * t + 1
            loss = (1 - t) * z + log_weight * (jnp.log1p(jnp.exp(neg_abs)) + jnp.maximum(-z, 0))
        else:
            loss = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(neg_abs))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    args = [t for t in (weight, pos_weight) if t is not None]
    return apply(fn, logit, label, *args, _name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(lp, t):
        if log_target:
            loss = jnp.exp(t) * (t - lp)
        else:
            loss = t * (jnp.log(jnp.maximum(t, 1e-30)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return apply(fn, input, label, _name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply(
        lambda a, b, t: _reduce(jnp.maximum(0.0, -t * (a - b) + margin), reduction),
        input, other, label, _name="margin_ranking")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply(
        lambda a, t: _reduce(jnp.where(t == 1, a, jnp.maximum(0.0, margin - a)), reduction),
        input, label, _name="hinge_embedding")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    def fn(a, b, t):
        cos = jnp.sum(a * b, -1) / (jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(t == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply(fn, input1, input2, label, _name="cosine_embedding")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos), p), -1) + epsilon, 1 / p)
        dn = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg), p), -1) + epsilon, 1 / p)
        if swap:
            dsn = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg), p), -1) + epsilon, 1 / p)
            dn = jnp.minimum(dn, dsn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply(fn, input, positive, negative, _name="triplet_margin")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, t, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)

    args = [normalizer] if normalizer is not None else []
    return apply(fn, logit, label, *args, _name="focal")


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply(
        lambda p, t: -t * jnp.log(p + epsilon) - (1 - t) * jnp.log(1 - p + epsilon),
        input, label, _name="log_loss")


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), input, label, _name="square_error")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean",
             norm_by_times=False):
    # CTC via the standard forward algorithm in log space (lax.scan over time)
    lp = log_probs._data.astype(jnp.float32)  # [T, B, C] paddle layout
    lbl = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
    il = input_lengths._data if isinstance(input_lengths, Tensor) else jnp.asarray(input_lengths)
    ll = label_lengths._data if isinstance(label_lengths, Tensor) else jnp.asarray(label_lengths)

    def fn(lp_):
        logp = jax.nn.log_softmax(lp_, axis=-1)
        T, B, C = logp.shape
        L = lbl.shape[1]
        S = 2 * L + 1
        ext = jnp.full((B, S), blank, dtype=lbl.dtype)
        ext = ext.at[:, 1::2].set(lbl)
        neg_inf = -1e30
        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(logp[0], ext[:, 1:2].astype(jnp.int32), axis=1)[:, 0])

        same = jnp.concatenate([jnp.ones((B, 2), bool),
                                ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a_shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext.astype(jnp.int32), axis=1)
            return merged + emit, None

        def scan_t(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, logp[t])
            alpha = jnp.where((t >= 1) & (t < il)[:, None], new_alpha, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(scan_t, alpha0, jnp.arange(T))
        end1 = 2 * ll - 1
        end2 = 2 * ll
        a1 = jnp.take_along_axis(alpha, end1[:, None].astype(jnp.int32), axis=1)[:, 0]
        a2 = jnp.take_along_axis(alpha, end2[:, None].astype(jnp.int32), axis=1)[:, 0]
        loss = -jnp.logaddexp(a1, a2)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(ll.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)

    return apply(fn, log_probs, _name="ctc_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, l):
        sim = a @ p.T
        lbl = l.reshape(-1, 1)
        target = (lbl == lbl.T).astype(jnp.float32)
        target = target / target.sum(-1, keepdims=True)
        ce = -jnp.sum(target * jax.nn.log_softmax(sim, -1), -1)
        reg = l2_reg * (jnp.sum(a * a) + jnp.sum(p * p)) / (2 * a.shape[0])
        return jnp.mean(ce) + reg

    return apply(fn, anchor, positive, labels, _name="npair")


def dice_loss(input, label, epsilon=1e-5, name=None):
    def fn(p, t):
        t1 = jax.nn.one_hot(t[..., 0].astype(jnp.int32), p.shape[-1], dtype=p.dtype)
        inter = jnp.sum(p * t1, axis=tuple(range(1, p.ndim)))
        union = jnp.sum(p, axis=tuple(range(1, p.ndim))) + jnp.sum(t1, axis=tuple(range(1, p.ndim)))
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return apply(fn, input, label, _name="dice")


def identity_loss(x, reduction="none", name=None):
    """Pass-through loss marker (reference ops.yaml identity_loss)."""
    if reduction in (0, "sum"):
        return apply(jnp.sum, x, _name="identity_loss")
    if reduction in (1, "mean"):
        return apply(jnp.mean, x, _name="identity_loss")
    return x


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace/CosFace-family margin softmax (reference
    `python/paddle/nn/functional/loss.py` margin_cross_entropy /
    `phi/kernels/margin_cross_entropy_kernel`): logits are cosines; the
    target class logit becomes cos(m1*theta + m2) - m3, scaled by s.
    Single-device dense path (the model-parallel variant lives in
    fleet's ParallelCrossEntropy)."""
    lbl = label._data if isinstance(label, Tensor) else jnp.asarray(label)
    lbl = lbl.reshape(-1)

    def fn(cos_t):
        c = jnp.clip(cos_t.astype(jnp.float32), -1.0, 1.0)
        theta = jnp.arccos(c)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(lbl, c.shape[-1], dtype=c.dtype)
        out = jnp.where(onehot > 0, target, c) * scale
        logp = jax.nn.log_softmax(out, axis=-1)
        loss = -jnp.take_along_axis(logp, lbl[:, None], axis=-1)[:, 0]
        sm = jnp.exp(logp)
        return _reduce(loss, reduction), sm

    loss, sm = apply(lambda a: fn(a), logits, _name="margin_cross_entropy")
    if return_softmax:
        return loss, sm
    return loss


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference
    `python/paddle/nn/functional/loss.py` hsigmoid_loss /
    `phi/kernels/hsigmoid_loss_kernel`). Default complete-binary-tree
    coding over num_classes, or custom (path_table, path_code)."""
    import numpy as np

    lbl = label._data if isinstance(label, Tensor) else jnp.asarray(label)
    lbl = lbl.reshape(-1)

    if path_table is None:
        # complete binary tree: internal nodes 0..num_classes-2; leaf c sits
        # at heap position num_classes-1+c; path = ancestors root->parent,
        # code = left(0)/right(1) turns (the reference's default coding)
        depth = int(np.ceil(np.log2(max(num_classes, 2))))
        tables, codes = [], []
        for c in range(num_classes):
            pos = num_classes - 1 + c
            pt, pc = [], []
            while pos > 0:
                parent = (pos - 1) // 2
                pt.append(parent)
                pc.append(float(pos == 2 * parent + 2))
                pos = parent
            pt, pc = pt[::-1], pc[::-1]
            pt += [-1] * (depth - len(pt))
            pc += [0.0] * (depth - len(pc))
            tables.append(pt[:depth])
            codes.append(pc[:depth])
        table = jnp.asarray(np.asarray(tables, np.int32))[lbl]
        code = jnp.asarray(np.asarray(codes, np.float32))[lbl]
    else:
        pt = path_table._data if isinstance(path_table, Tensor) \
            else jnp.asarray(path_table)
        pc = path_code._data if isinstance(path_code, Tensor) \
            else jnp.asarray(path_code)
        table, code = pt[lbl], pc[lbl].astype(jnp.float32)

    valid = (table >= 0).astype(jnp.float32)
    safe_t = jnp.maximum(table, 0)

    def fn(x, w, *b):
        # w: [num_internal_nodes, feature]; per-sample node rows
        wrows = w[safe_t]                       # [B, D, feat]
        logit = jnp.einsum("bdf,bf->bd", wrows, x.astype(jnp.float32))
        if b:
            logit = logit + b[0].reshape(-1)[safe_t]
        # BCE-with-logits against the path code, masked to real path length
        lo = jnp.maximum(logit, 0) - logit * code + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))
        return jnp.sum(lo * valid, axis=-1, keepdims=True)

    args = (input, weight) + ((bias,) if bias is not None else ())
    return apply(fn, *args, _name="hsigmoid_loss")


def warprnnt(input, label, input_lengths, label_lengths, blank=0,
             fastemit_lambda=0.0, name=None):
    """RNN-Transducer loss (reference warprnnt op wrapping warp-rnnt;
    python api `F.rnnt_loss`). input [B, T, U+1, V] LOG-PROBS (or logits
    — normalized internally), label [B, U].

    TPU-native: the forward algorithm is a lax.scan over time frames,
    vectorized over the label dimension and the batch — the whole lattice
    stays on device and jax AD provides the gradient (warp-rnnt's
    hand-written backward). alpha[t, u] = logaddexp(
    alpha[t-1, u] + blank(t-1, u), alpha[t, u-1] + y(t, u-1));
    loss = -(alpha[T-1, U] + blank(T-1, U)).

    FastEmit (Yu et al. 2021; reference warprnnt kernel applies it as a
    (1+lambda) scaling of the emission-edge gradients): implemented as
    loss + lambda * loss_em where loss_em is the SAME forward value with
    the blank log-probs held constant (stop_gradient) — its gradient
    flows only through emission edges, which is exactly the per-edge
    scaling the kernel hand-codes."""
    import jax

    def fn(logits, lab, in_len, lab_len):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        base = _rnnt_forward(logp, lab, in_len, lab_len, blank)
        if fastemit_lambda:
            em = _rnnt_forward(logp, lab, in_len, lab_len, blank,
                               sg_blank=True)
            return base + fastemit_lambda * em
        return base

    def _rnnt_forward(logp, lab, in_len, lab_len, blank, sg_blank=False):
        B, T, U1, V = logp.shape
        U = U1 - 1
        lab = lab.astype(jnp.int32)
        blank_lp = logp[..., blank]                      # [B, T, U+1]
        if sg_blank:
            blank_lp = jax.lax.stop_gradient(blank_lp)
        # y_lp[b, t, u] = logp of emitting label[u] from lattice row u
        y_lp = jnp.take_along_axis(
            logp[:, :, :U, :],
            jnp.broadcast_to(lab[:, None, :, None], (B, T, U, 1)),
            axis=3)[..., 0]
        NEG = jnp.float32(-1e30)

        def time_step(alpha_prev, t):
            # horizontal (same t): alpha[t, u] from alpha[t, u-1] + y
            # seeded by the vertical move alpha[t-1, u] + blank(t-1, u)
            from_top = jnp.where(
                t > 0, alpha_prev + blank_lp[:, jnp.maximum(t - 1, 0)],
                jnp.where(jnp.arange(U1)[None] == 0, 0.0, NEG))

            def hstep(carry, u):
                prev = carry  # alpha[t, u-1] per batch
                emit_lp = jnp.where(
                    u > 0,
                    y_lp[:, t, jnp.maximum(u - 1, 0)], NEG)
                a = jnp.logaddexp(from_top[:, u], prev + emit_lp)
                return a, a

            _, cols = jax.lax.scan(hstep, jnp.full((B,), NEG),
                                   jnp.arange(U1))
            alpha_t = cols.T  # [B, U+1]
            return alpha_t, alpha_t

        _, alphas = jax.lax.scan(time_step, jnp.full((B, U1), NEG),
                                 jnp.arange(T))  # [T, B, U+1]
        t_idx = (in_len.astype(jnp.int32) - 1)
        u_idx = lab_len.astype(jnp.int32)
        bidx = jnp.arange(B)
        final = alphas[t_idx, bidx, u_idx] \
            + blank_lp[bidx, t_idx, u_idx]
        return -final

    from paddle_tpu.core.tensor import apply as _apply

    return _apply(fn, input, label, input_lengths, label_lengths,
                  _name="warprnnt")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """python api over warprnnt (reference F.rnnt_loss; its 0.001
    fastemit default intentionally differs from the raw op's 0.0)."""
    loss = warprnnt(input, label, input_lengths, label_lengths,
                    blank=blank, fastemit_lambda=fastemit_lambda)
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss
