"""Convolutions via lax.conv_general_dilated — XLA lowers these onto the MXU
(reference op surface: `python/paddle/nn/functional/conv.py`)."""

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import apply


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    strides = _pair(stride, n)
    dil = _pair(dilation, n)
    pad = _padding(padding, n)
    if data_format in ("NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + "DHW"[3 - n:]
    else:
        lhs_spec = "N" + "DHW"[3 - n:] + "C"
    rhs_spec = "OI" + "DHW"[3 - n:]
    out_spec = lhs_spec
    dn = (lhs_spec, rhs_spec, out_spec)

    def fn(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dil, dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=jnp.float32 if a.dtype == jnp.bfloat16 else None)
        if out.dtype != a.dtype:
            out = out.astype(a.dtype)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[lhs_spec.index("C")] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    if bias is not None:
        return apply(fn, x, weight, bias, _name=f"conv{n}d")
    return apply(fn, x, weight, _name=f"conv{n}d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, data_format, output_size=None):
    strides = _pair(stride, n)
    dil = _pair(dilation, n)
    opad = _pair(output_padding, n)
    if data_format in ("NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + "DHW"[3 - n:]
    else:
        lhs_spec = "N" + "DHW"[3 - n:] + "C"
    # paddle conv_transpose weight layout: [in, out/groups, *k]
    rhs_spec = "IO" + "DHW"[3 - n:]
    dn = (lhs_spec, rhs_spec, lhs_spec)

    if isinstance(padding, str):
        pad_cfg = padding.upper()
    else:
        p = _padding(padding, n)
        # transposed conv padding: lax handles via negative-lookahead formula
        pad_cfg = [
            (dil[i] * (weight.shape[2 + i] - 1) - p[i][0],
             dil[i] * (weight.shape[2 + i] - 1) - p[i][1] + opad[i])
            for i in range(n)
        ]

    def fn(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, jnp.flip(w, axis=tuple(range(2, 2 + n))),
            window_strides=(1,) * n, padding=pad_cfg,
            lhs_dilation=strides, rhs_dilation=dil,
            dimension_numbers=(lhs_spec, "OI" + "DHW"[3 - n:], lhs_spec),
            feature_group_count=groups) if groups == 1 else _grouped(a, w, b)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[lhs_spec.index("C")] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    def _grouped(a, w, b):
        # split channels per group and run each; groups are rare in transpose
        a_groups = jnp.split(a, groups, axis=lhs_spec.index("C"))
        w_groups = jnp.split(w, groups, axis=0)
        outs = []
        for ag, wg in zip(a_groups, w_groups):
            outs.append(jax.lax.conv_general_dilated(
                ag, jnp.flip(wg, axis=tuple(range(2, 2 + n))),
                window_strides=(1,) * n, padding=pad_cfg,
                lhs_dilation=strides, rhs_dilation=dil,
                dimension_numbers=(lhs_spec, "OI" + "DHW"[3 - n:], lhs_spec)))
        return jnp.concatenate(outs, axis=lhs_spec.index("C"))

    # weight [in, out/groups, *k] -> as "OI" we need [out, in/groups, *k]:
    # swap and handle groups by transposing per-group
    def prep(w):
        return jnp.swapaxes(w, 0, 1)

    import paddle_tpu as _p

    wt = apply(prep, weight, _name="convT_w")
    if bias is not None:
        return apply(fn, x, wt, bias, _name=f"conv{n}d_transpose")
    return apply(fn, x, wt, _name=f"conv{n}d_transpose")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 1, data_format, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 3, data_format, output_size)
