"""Activation functionals (reference: `python/paddle/nn/functional/activation.py`).

All map to XLA-fusable elementwise primitives; XLA fuses them into adjacent
matmuls so none of these costs an extra HBM round-trip under jit.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, apply


def relu(x, name=None):
    return apply(jax.nn.relu, x, _name="relu")


def relu_(x, name=None):
    out = relu(x)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    x.stop_gradient = out.stop_gradient
    return x


def relu6(x, name=None):
    return apply(jax.nn.relu6, x, _name="relu6")


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, x, _name="sigmoid")


def tanh(x, name=None):
    return apply(jnp.tanh, x, _name="tanh")


def gelu(x, approximate=False, name=None):
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate), x, _name="gelu")


def silu(x, name=None):
    return apply(jax.nn.silu, x, _name="silu")


swish = silu


def mish(x, name=None):
    return apply(lambda a: a * jnp.tanh(jax.nn.softplus(a)), x, _name="mish")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope), x, _name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(a, w):
        if w.size > 1:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a >= 0, a, w * a)

    return apply(fn, x, weight, _name="prelu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    if training:
        from paddle_tpu.framework import random as _rng

        key_t = _rng.next_key_tensor()

        def fn(a, key):
            neg = jax.random.uniform(key, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, neg * a)

        return apply(fn, x, key_t, _name="rrelu")
    mid = (lower + upper) / 2.0
    return apply(lambda a: jnp.where(a >= 0, a, mid * a), x, _name="rrelu")


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha), x, _name="elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x, _name="selu")


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha), x, _name="celu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda a: jnp.clip(a, min, max), x, _name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x, _name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply(
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)),
        x, _name="softshrink")


def tanhshrink(x, name=None):
    return apply(lambda a: a - jnp.tanh(a), x, _name="tanhshrink")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x, _name="hardsigmoid")


def hardswish(x, name=None):
    return apply(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x, _name="hardswish")


def softplus(x, beta=1, threshold=20, name=None):
    return apply(
        lambda a: jnp.where(beta * a > threshold, a, jax.nn.softplus(beta * a) / beta),
        x, _name="softplus")


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, x, _name="softsign")


def softmax(x, axis=-1, dtype=None, name=None):
    from paddle_tpu.framework import dtypes

    dt = dtypes.convert_dtype(dtype)

    def fn(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.softmax(a, axis=axis)

    return apply(fn, x, _name="softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis, dtype)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


def log_softmax(x, axis=-1, dtype=None, name=None):
    from paddle_tpu.framework import dtypes

    dt = dtypes.convert_dtype(dtype)

    def fn(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.log_softmax(a, axis=axis)

    return apply(fn, x, _name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from paddle_tpu.framework import random as _rng

    key_t = _rng.next_key_tensor()

    def fn(a, key):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y).at[
                tuple(jnp.indices(y.shape)[i] if i != axis % y.ndim else jnp.broadcast_to(idx, y.shape)
                      for i in range(y.ndim))
            ].set(0)
            hard_y = (jnp.arange(y.shape[axis]).reshape(
                [-1 if i == axis % y.ndim else 1 for i in range(y.ndim)]) == idx).astype(y.dtype)
            return jax.lax.stop_gradient(hard_y - y) + y
        return y

    return apply(fn, x, key_t, _name="gumbel_softmax")


def maxout(x, groups, axis=1, name=None):
    def fn(a):
        shape = list(a.shape)
        c = shape[axis]
        shape[axis:axis + 1] = [c // groups, groups]
        return jnp.max(a.reshape(shape), axis=axis + 1)

    return apply(fn, x, _name="maxout")


def glu(x, axis=-1, name=None):
    return apply(lambda a: jax.nn.glu(a, axis=axis), x, _name="glu")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply(lambda a: jnp.where(a > threshold, a, value), x, _name="thresholded_relu")


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, x, _name="log_sigmoid")


def swiglu(x, y=None, name=None):
    """silu(x) * y; with y=None, x is split in half on the last dim
    (reference ops.yaml swiglu — the fused SwiGLU the Llama MLP uses)."""
    if y is None:
        def fn(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2

        return apply(fn, x, _name="swiglu")
    return apply(lambda a, b: jax.nn.silu(a) * b, x, y, _name="swiglu")
