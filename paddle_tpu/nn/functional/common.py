"""Common functionals: linear, dropout, padding, embedding, one_hot,
interpolate, unfold (reference: `python/paddle/nn/functional/common.py`,
`input.py`)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor, apply
from paddle_tpu.framework import random as _rng


def linear(x, weight, bias=None, name=None):
    # paddle stores weight as [in, out] (reference nn/layer/common.py Linear)
    if bias is not None:
        return apply(lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias, _name="linear")
    return apply(lambda a, w: jnp.matmul(a, w), x, weight, _name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if mode == "upscale_in_train" else x * (1.0 - p)
    # the key rides the waist as a real input (not a closure): SOT capture
    # marks it refresh-on-replay so compiled steps re-draw the mask
    key_t = _rng.next_key_tensor()

    def fn(a, key):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in [ax % a.ndim for ax in axes] else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply(fn, x, key_t, _name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    a = (1.0 - p + p * alpha_p ** 2 * (1.0 - p)) ** -0.5
    b = -a * alpha_p * p
    key_t = _rng.next_key_tensor()

    def fn(t, key):
        keep = jax.random.bernoulli(key, 1.0 - p, t.shape)
        return (a * jnp.where(keep, t, alpha_p) + b).astype(t.dtype)

    return apply(fn, x, key_t, _name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, max_norm=None, norm_type=2.0, name=None):
    idx = x._data if isinstance(x, Tensor) else jnp.asarray(x)

    def fn(w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (idx != padding_idx)[..., None]
            out = jnp.where(mask, out, 0.0)
        return out

    return apply(fn, weight, _name="embedding")


def one_hot(x, num_classes, name=None):
    idx = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.nn.one_hot(idx, num_classes, dtype=jnp.float32))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._data if isinstance(prior_dist, Tensor) else jnp.asarray(prior_dist)
            return (1.0 - epsilon) * l + epsilon * pd
        return (1.0 - epsilon) * l + epsilon / k

    return apply(fn, label, _name="label_smooth")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=True, name=None):
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim

    if len(pad) == 2 * nd:
        # full-rank paddle format: [d0_l, d0_r, d1_l, d1_r, ...]
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial spec applies to spatial dims per data_format, innermost last
        n_spatial = len(pad) // 2
        width = [(0, 0)] * nd
        if data_format.startswith("NC"):
            spatial = list(range(2, nd))
        else:
            spatial = list(range(1, nd - 1))
        spatial = spatial[-n_spatial:]
        for i, d in enumerate(reversed(spatial)):
            width[d] = (pad[2 * i], pad[2 * i + 1])

    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]

    def fn(a):
        if jmode == "constant":
            return jnp.pad(a, width, mode="constant", constant_values=value)
        return jnp.pad(a, width, mode=jmode)

    return apply(fn, x, _name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    nd = x.ndim
    cf = data_format.startswith("NC")
    spatial = x.shape[2:] if cf else x.shape[1:-1]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_spatial = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in size]
    else:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        out_spatial = [int(s * f) for s, f in zip(spatial, scale_factor)]

    method = {"nearest": "nearest", "bilinear": "linear", "trilinear": "linear",
              "linear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def fn(a):
        if cf:
            shape = list(a.shape[:2]) + out_spatial
        else:
            shape = [a.shape[0]] + out_spatial + [a.shape[-1]]
        return jax.image.resize(a, tuple(shape), method=method)

    return apply(fn, x, _name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 4
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]

    def fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[1]), (pd[2], pd[3])))
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=tuple(ks), window_strides=tuple(st),
            padding="VALID", rhs_dilation=tuple(dl),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, c * ks[0] * ks[1], -1)

    return apply(fn, x, _name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    # inverse of unfold via scatter-add
    os = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2

    def fn(a):
        n, ckk, l = a.shape
        c = ckk // (ks[0] * ks[1])
        oh = (os[0] + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
        ow = (os[1] + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
        a = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, os[0] + 2 * pd[0], os[1] + 2 * pd[1]), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                hi = i * dl[0]
                wi = j * dl[1]
                out = out.at[:, :, hi:hi + oh * st[0]:st[0], wi:wi + ow * st[1]:st[1]].add(a[:, :, i, j])
        return out[:, :, pd[0]:out.shape[2] - pd[0], pd[1]:out.shape[3] - pd[1]]

    return apply(fn, x, _name="fold")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.sqrt(jnp.sum(a * a, axis=axis)) * jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(den, eps)

    return apply(fn, x1, x2, _name="cosine_similarity")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, c // (r * r), r, r)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        return a.reshape(n, h * r, w * r, c // (r * r))

    return apply(fn, x, _name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = jnp.transpose(a, (0, 1, 3, 5, 2, 4))
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = jnp.transpose(a, (0, 2, 4, 5, 1, 3))
        return a.reshape(n, h // r, w // r, c * r * r)

    return apply(fn, x, _name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            return jnp.swapaxes(a, 1, 2).reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        return jnp.swapaxes(a, 3, 4).reshape(n, h, w, c)

    return apply(fn, x, _name="channel_shuffle")


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *bb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bb:
            out = out + bb[0]
        return out

    if bias is not None:
        return apply(fn, x1, x2, weight, bias, _name="bilinear")
    return apply(fn, x1, x2, weight, _name="bilinear")


def pad3d(x, paddings, mode="constant", value=0.0, data_format="NCDHW", name=None):
    """5-D padding (reference ops.yaml pad3d). paddings: 6 ints
    [front, back, top, bottom, left, right] in reference order
    [left, right, top, bottom, front, back] for W/H/D."""
    l, r, t, b, f, bk = paddings

    def fn(a):
        if data_format == "NCDHW":
            cfg = [(0, 0), (0, 0), (f, bk), (t, b), (l, r)]
        else:  # NDHWC
            cfg = [(0, 0), (f, bk), (t, b), (l, r), (0, 0)]
        if mode == "constant":
            return jnp.pad(a, cfg, constant_values=value)
        m = {"reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
        return jnp.pad(a, cfg, mode=m)

    return apply(fn, x, _name="pad3d")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Affine sampling grid (reference ops.yaml affine_grid). theta:
    [N, 2, 3] -> grid [N, H, W, 2] (4-D) or [N, 3, 4] -> [N, D, H, W, 3]."""
    shape = [int(s) for s in
             (out_shape.numpy() if hasattr(out_shape, "numpy") else out_shape)]

    def lin(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        half = 1.0 - 1.0 / n
        return jnp.linspace(-half, half, n)

    def fn(th):
        if len(shape) == 4:
            n, _, h, w = shape
            ys, xs = jnp.meshgrid(lin(h), lin(w), indexing="ij")
            base = jnp.stack([xs, ys, jnp.ones_like(xs)], -1)  # [H, W, 3]
            return jnp.einsum("hwk,nck->nhwc", base, th)
        n, _, d, h, w = shape
        zs, ys, xs = jnp.meshgrid(lin(d), lin(h), lin(w), indexing="ij")
        base = jnp.stack([xs, ys, zs, jnp.ones_like(xs)], -1)
        return jnp.einsum("dhwk,nck->ndhwc", base, th)

    return apply(fn, theta, _name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x (NCHW) at grid (N,H',W',2) locations in [-1,1] (reference
    ops.yaml grid_sample). Gathers vectorize cleanly on TPU."""
    def fn(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]

        def unnorm(u, size):
            if align_corners:
                return (u + 1) * (size - 1) / 2
            return ((u + 1) * size - 1) / 2

        fx, fy = unnorm(gx, w), unnorm(gy, h)

        def sample_at(ix, iy):
            inside = ((ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1))
            if padding_mode == "border":
                ixc = jnp.clip(ix, 0, w - 1)
                iyc = jnp.clip(iy, 0, h - 1)
                inside = jnp.ones_like(inside)
            elif padding_mode == "reflection":
                def reflect(u, size):
                    # reflect into [0, size-1] with period 2(size-1)
                    if size == 1:
                        return jnp.zeros_like(u)
                    span = 2.0 * (size - 1)
                    u = jnp.mod(jnp.abs(u), span)
                    return jnp.minimum(u, span - u)

                ixc = reflect(ix, w)
                iyc = reflect(iy, h)
                inside = jnp.ones_like(inside)
            else:
                ixc = jnp.clip(ix, 0, w - 1)
                iyc = jnp.clip(iy, 0, h - 1)
            # a: [N,C,H,W]; gather per batch with advanced indexing
            bidx = jnp.arange(n).reshape(n, 1, 1)
            vals = a[bidx, :, iyc.astype(jnp.int32), ixc.astype(jnp.int32)]
            # vals: [N, H', W', C] -> mask and move C forward
            vals = jnp.where(inside[..., None], vals, 0.0)
            return jnp.moveaxis(vals, -1, 1)

        if mode == "nearest":
            return sample_at(jnp.round(fx), jnp.round(fy))
        x0, y0 = jnp.floor(fx), jnp.floor(fy)
        x1, y1 = x0 + 1, y0 + 1
        wa = (x1 - fx) * (y1 - fy)
        wb = (fx - x0) * (y1 - fy)
        wc = (x1 - fx) * (fy - y0)
        wd = (fx - x0) * (fy - y0)
        return (sample_at(x0, y0) * wa[:, None] +
                sample_at(x1, y0) * wb[:, None] +
                sample_at(x0, y1) * wc[:, None] +
                sample_at(x1, y1) * wd[:, None])

    return apply(fn, x, grid, _name="grid_sample")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """mask[i.., j] = j < x[i..] (reference `python/paddle/nn/functional/
    extension.py` sequence_mask / `phi/kernels/sequence_mask_kernel`).
    maxlen=None uses x.max() — eager only (data-dependent shape); pass a
    static maxlen under jit."""
    from paddle_tpu.framework import dtypes as _dt

    lens = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if maxlen is None:
        maxlen = int(jnp.max(lens))
    rng = jnp.arange(int(maxlen))
    mask = rng[None, :] < lens.reshape(-1, 1)
    mask = mask.reshape(tuple(lens.shape) + (int(maxlen),))
    return Tensor(mask.astype(_dt.convert_dtype(dtype)))


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM temporal shift (reference `python/paddle/nn/functional/
    extension.py` temporal_shift / `phi/kernels/temporal_shift_kernel`):
    the first shift_ratio of channels shifts t-1, the second t+1, the rest
    stay. x: [N*T, C, H, W]."""
    def fn(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        pad = jnp.pad(v, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
        fwd = pad[:, :seg_num, :c1]        # channel block shifted from t-1
        bwd = pad[:, 2:, c1:c2]            # shifted from t+1
        keep = v[:, :, c2:]
        out = jnp.concatenate([fwd, bwd, keep], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply(fn, x, _name="temporal_shift")


def gather_tree(ids, parents):
    """Beam-search backtrack (reference `python/paddle/nn/functional/
    extension.py` gather_tree / `phi/kernels/gather_tree_kernel`): walk
    parent pointers from the last step so each beam holds its full
    ancestry. ids/parents: [T, batch, beam]."""
    def fn(idv, par):
        T = idv.shape[0]
        beams = jnp.arange(idv.shape[2])[None, :]
        beams = jnp.broadcast_to(beams, idv.shape[1:])

        def step(carry, t):
            beam = carry
            tok = jnp.take_along_axis(idv[t], beam, axis=-1)
            beam = jnp.take_along_axis(par[t], beam, axis=-1)
            return beam, tok

        _, toks = jax.lax.scan(step, beams, jnp.arange(T - 1, -1, -1))
        return toks[::-1]

    return apply(fn, ids, parents, _name="gather_tree")
