"""Pooling via lax.reduce_window (reference: `python/paddle/nn/functional/pooling.py`)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor, apply


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _pad_cfg(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]


def _reduce_pool(x, kernel, stride, padding, n, init, op, data_format, count_include_pad=True, is_avg=False,
                 ceil_mode=False):
    k = _tuple(kernel, n)
    s = _tuple(stride if stride is not None else kernel, n)
    cf = data_format.startswith("NC")
    if cf:
        window = (1, 1) + k
        strides = (1, 1) + s
    else:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
    pad = _pad_cfg(padding, n)
    if isinstance(pad, str):
        pad_full = pad
    else:
        pad_full = ([(0, 0), (0, 0)] + pad) if cf else ([(0, 0)] + pad + [(0, 0)])

    def fn(a):
        if is_avg:
            summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pad_full)
            if count_include_pad or isinstance(pad_full, str):
                denom = np.prod(k)
                return summed / denom
            ones = jnp.ones_like(a)
            denom = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pad_full)
            return summed / denom
        return jax.lax.reduce_window(a, init, op, window, strides, pad_full)

    return apply(fn, x, _name="pool")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    out = _reduce_pool(x, kernel_size, stride, padding, 1, -jnp.inf, jax.lax.max, "NCL")
    return (out, _pool_mask(x, out)) if return_mask else out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCHW", name=None):
    out = _reduce_pool(x, kernel_size, stride, padding, 2, -jnp.inf, jax.lax.max, data_format)
    return (out, _pool_mask(x, out)) if return_mask else out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCDHW", name=None):
    out = _reduce_pool(x, kernel_size, stride, padding, 3, -jnp.inf, jax.lax.max, data_format)
    return (out, _pool_mask(x, out)) if return_mask else out


def _pool_mask(x, out):
    # best-effort indices (paddle returns argmax positions); rarely consumed
    return Tensor(jnp.zeros(out.shape, jnp.int64))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _reduce_pool(x, kernel_size, stride, padding, 1, 0.0, jax.lax.add, "NCL",
                        count_include_pad=not exclusive, is_avg=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    return _reduce_pool(x, kernel_size, stride, padding, 2, 0.0, jax.lax.add, data_format,
                        count_include_pad=not exclusive, is_avg=True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    return _reduce_pool(x, kernel_size, stride, padding, 3, 0.0, jax.lax.add, data_format,
                        count_include_pad=not exclusive, is_avg=True)


def _adaptive(x, output_size, n, data_format, is_avg):
    cf = data_format.startswith("NC")
    os = _tuple(output_size, n)
    spatial = x.shape[2:2 + n] if cf else x.shape[1:1 + n]
    os = tuple(o if o is not None else s for o, s in zip(os, spatial))

    def fn(a):
        out = a
        for d, (inp, o) in enumerate(zip(spatial, os)):
            ax = (2 + d) if cf else (1 + d)
            if inp % o == 0:
                k = inp // o
                shape = list(out.shape)
                shape[ax:ax + 1] = [o, k]
                r = out.reshape(shape)
                out = jnp.mean(r, axis=ax + 1) if is_avg else jnp.max(r, axis=ax + 1)
            else:
                # general case: gather windows
                starts = (np.arange(o) * inp) // o
                ends = -(-((np.arange(o) + 1) * inp) // o)
                slices = []
                for st, en in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(st), int(en), axis=ax)
                    seg = jnp.mean(seg, axis=ax, keepdims=True) if is_avg else jnp.max(seg, axis=ax, keepdims=True)
                    slices.append(seg)
                out = jnp.concatenate(slices, axis=ax)
        return out

    return apply(fn, x, _name="adaptive_pool")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "NCL", True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, data_format, True)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, data_format, True)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 1, "NCL", False)
    return (out, _pool_mask(x, out)) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 2, "NCHW", False)
    return (out, _pool_mask(x, out)) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 3, "NCDHW", False)
    return (out, _pool_mask(x, out)) if return_mask else out


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCHW", name=None):
    p = float(norm_type)

    def powfn(a):
        return jnp.power(jnp.abs(a), p)

    from paddle_tpu.core.tensor import apply as _apply

    powed = _apply(powfn, x, _name="lp_pow")
    pooled = _reduce_pool(powed, kernel_size, stride, padding, 2, 0.0, jax.lax.add, data_format,
                          is_avg=False)
    return _apply(lambda a: jnp.power(a, 1.0 / p), pooled, _name="lp_root")
