"""Pooling via lax.reduce_window (reference: `python/paddle/nn/functional/pooling.py`)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor, apply


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _pad_cfg(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]


def _reduce_pool(x, kernel, stride, padding, n, init, op, data_format, count_include_pad=True, is_avg=False,
                 ceil_mode=False):
    k = _tuple(kernel, n)
    s = _tuple(stride if stride is not None else kernel, n)
    cf = data_format.startswith("NC")
    if cf:
        window = (1, 1) + k
        strides = (1, 1) + s
    else:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
    pad = _pad_cfg(padding, n)
    if isinstance(pad, str):
        pad_full = pad
    else:
        pad_full = ([(0, 0), (0, 0)] + pad) if cf else ([(0, 0)] + pad + [(0, 0)])

    def fn(a):
        if is_avg:
            summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pad_full)
            if count_include_pad or isinstance(pad_full, str):
                denom = np.prod(k)
                return summed / denom
            ones = jnp.ones_like(a)
            denom = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pad_full)
            return summed / denom
        return jax.lax.reduce_window(a, init, op, window, strides, pad_full)

    return apply(fn, x, _name="pool")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    if return_mask:
        return _max_pool_with_index(x, kernel_size, stride, padding, 1, "NCL")
    return _reduce_pool(x, kernel_size, stride, padding, 1, -jnp.inf, jax.lax.max, "NCL")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_with_index(x, kernel_size, stride, padding, 2,
                                    data_format)
    return _reduce_pool(x, kernel_size, stride, padding, 2, -jnp.inf, jax.lax.max, data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_with_index(x, kernel_size, stride, padding, 3,
                                    data_format)
    return _reduce_pool(x, kernel_size, stride, padding, 3, -jnp.inf, jax.lax.max, data_format)


def _max_pool_with_index(x, kernel, stride, padding, n, data_format):
    """Max pooling returning REAL argmax indices (flat offset within each
    (N, C) spatial slab — the reference max_poolNd_with_index semantics,
    `phi/kernels/pool_kernel` MaxPoolWithIndex), the exact inverse input
    max_unpoolNd expects. Values go through the standard (differentiable)
    reduce_window max; indices via sliding-window patches + argmax under
    stop_gradient (indices carry no gradient)."""
    k = _tuple(kernel, n)
    st = _tuple(stride if stride is not None else kernel, n)
    cf = data_format.startswith("NC")
    pad = _pad_cfg(padding, n)
    if isinstance(pad, str):
        raise ValueError("return_mask needs explicit int padding")
    pad_lo = [p[0] for p in pad]

    def fn(a):
        if not cf:  # normalize to channels-first
            perm = (0, n + 1) + tuple(range(1, n + 1))
            a = jnp.transpose(a, perm)
        N, C = a.shape[:2]
        sp = a.shape[2:]
        window = (1, 1) + k
        strides = (1, 1) + st
        pad_full = [(0, 0), (0, 0)] + list(pad)
        out = jax.lax.reduce_window(a, -jnp.inf, jax.lax.max, window,
                                    strides, pad_full)

        a_sg = jax.lax.stop_gradient(a)
        a_pad = jnp.pad(a_sg, pad_full, constant_values=-jnp.inf)
        pats = jax.lax.conv_general_dilated_patches(
            a_pad, filter_shape=k, window_strides=st,
            padding=[(0, 0)] * n)
        osp = pats.shape[2:]
        prodk = int(np.prod(k))
        # feature dim is (C, *k) with C slowest
        pats = pats.reshape((N, C, prodk) + osp)
        off = jnp.argmax(pats, axis=2)  # within-window offset, k-row-major

        # decompose the k-major offset into per-dim deltas, add the window
        # origin, convert to a flat index over the ORIGINAL spatial dims
        flat = jnp.zeros_like(off)
        rem = off
        for i in range(n):
            tail = int(np.prod(k[i + 1:]))
            dk = rem // tail
            rem = rem % tail
            grid = jnp.arange(osp[i]) * st[i] - pad_lo[i]
            shape = [1] * off.ndim
            shape[2 + i] = osp[i]
            pos = jnp.clip(dk + grid.reshape(shape), 0, sp[i] - 1)
            flat = flat * sp[i] + pos
        idx = flat.astype(jnp.int64)
        if not cf:
            perm_back = (0,) + tuple(range(2, n + 2)) + (1,)
            out = jnp.transpose(out, perm_back)
            idx = jnp.transpose(idx, perm_back)
        return out, idx

    return apply(fn, x, _name=f"max_pool{n}d_with_index")

def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _reduce_pool(x, kernel_size, stride, padding, 1, 0.0, jax.lax.add, "NCL",
                        count_include_pad=not exclusive, is_avg=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    return _reduce_pool(x, kernel_size, stride, padding, 2, 0.0, jax.lax.add, data_format,
                        count_include_pad=not exclusive, is_avg=True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    return _reduce_pool(x, kernel_size, stride, padding, 3, 0.0, jax.lax.add, data_format,
                        count_include_pad=not exclusive, is_avg=True)


def _adaptive(x, output_size, n, data_format, is_avg):
    cf = data_format.startswith("NC")
    os = _tuple(output_size, n)
    spatial = x.shape[2:2 + n] if cf else x.shape[1:1 + n]
    os = tuple(o if o is not None else s for o, s in zip(os, spatial))

    def fn(a):
        out = a
        for d, (inp, o) in enumerate(zip(spatial, os)):
            ax = (2 + d) if cf else (1 + d)
            if inp % o == 0:
                k = inp // o
                shape = list(out.shape)
                shape[ax:ax + 1] = [o, k]
                r = out.reshape(shape)
                out = jnp.mean(r, axis=ax + 1) if is_avg else jnp.max(r, axis=ax + 1)
            else:
                # general case: gather windows
                starts = (np.arange(o) * inp) // o
                ends = -(-((np.arange(o) + 1) * inp) // o)
                slices = []
                for st, en in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(st), int(en), axis=ax)
                    seg = jnp.mean(seg, axis=ax, keepdims=True) if is_avg else jnp.max(seg, axis=ax, keepdims=True)
                    slices.append(seg)
                out = jnp.concatenate(slices, axis=ax)
        return out

    return apply(fn, x, _name="adaptive_pool")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "NCL", True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, data_format, True)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, data_format, True)


def _adaptive_max_with_index(x, output_size, n, data_format):
    """return_mask path: when every spatial dim divides the output size the
    adaptive windows are uniform, so it IS a regular max pool — reuse the
    real-index pooling. Ragged windows would need per-window argmax; raise
    rather than return fake indices."""
    cf = data_format.startswith("NC")
    os_ = _tuple(output_size, n)
    spatial = x.shape[2:2 + n] if cf else x.shape[1:1 + n]
    os_ = tuple(o if o is not None else sdim
                for o, sdim in zip(os_, spatial))
    if any(inp % o != 0 for inp, o in zip(spatial, os_)):
        raise NotImplementedError(
            "adaptive_max_pool(return_mask=True) needs input spatial dims "
            f"divisible by output_size (got {tuple(spatial)} -> {os_}): "
            "ragged adaptive windows have no uniform argmax indices")
    k = tuple(inp // o for inp, o in zip(spatial, os_))
    return _max_pool_with_index(x, k, k, 0, n, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_index(x, output_size, 1, "NCL")
    return _adaptive(x, output_size, 1, "NCL", False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_index(x, output_size, 2, "NCHW")
    return _adaptive(x, output_size, 2, "NCHW", False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_with_index(x, output_size, 3, "NCDHW")
    return _adaptive(x, output_size, 3, "NCDHW", False)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCHW", name=None):
    p = float(norm_type)

    def powfn(a):
        return jnp.power(jnp.abs(a), p)

    from paddle_tpu.core.tensor import apply as _apply

    powed = _apply(powfn, x, _name="lp_pow")
    pooled = _reduce_pool(powed, kernel_size, stride, padding, 2, 0.0, jax.lax.add, data_format,
                          is_avg=False)
    return _apply(lambda a: jnp.power(a, 1.0 / p), pooled, _name="lp_root")


def _max_unpool(x, indices, ndim, kernel_size, stride, padding, output_size,
                data_format, name):
    """Scatter pooled values back to their argmax positions (reference
    `python/paddle/nn/functional/pooling.py` max_unpool2d/3d,
    `phi/kernels/unpool_kernel`). `indices` are flat offsets within each
    (N, C) spatial slab, as produced by max_poolNd(return_mask=True)."""
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size,) * ndim
    if stride is None:
        stride = kernel_size
    elif isinstance(stride, int):
        stride = (stride,) * ndim
    pad = (padding,) * ndim if isinstance(padding, int) else tuple(padding)

    def out_shape(in_sp):
        if output_size is not None:
            sp = tuple(int(s) for s in output_size)[-ndim:]
            return sp
        return tuple((in_sp[i] - 1) * stride[i] - 2 * pad[i] + kernel_size[i]
                     for i in range(ndim))

    cf = data_format.startswith("NC")

    def fn(a, idx):
        if not cf:
            perm = (0, a.ndim - 1) + tuple(range(1, a.ndim - 1))
            a = jnp.transpose(a, perm)
            idx = jnp.transpose(idx, perm)
        n, c = a.shape[:2]
        sp = out_shape(a.shape[2:])
        flat_len = 1
        for s in sp:
            flat_len *= s
        av = a.reshape(n, c, -1)
        iv = idx.reshape(n, c, -1).astype(jnp.int32)
        out = jnp.zeros((n, c, flat_len), a.dtype)
        out = jax.vmap(jax.vmap(
            lambda o, i, v: o.at[i].set(v)))(out, iv, av)
        out = out.reshape((n, c) + sp)
        if not cf:
            out = jnp.transpose(out, (0,) + tuple(range(2, out.ndim)) + (1,))
        return out

    from paddle_tpu.core.tensor import apply as _apply

    return _apply(fn, x, indices, _name=f"max_unpool{ndim}d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format, name)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format, name)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format, name)


def _fractional_bounds(n_in, n_out, u):
    """Pseudo-random pooling boundaries (Graham 2014; reference
    fractional_max_pool kernels): alpha = n_in / n_out, index(i) =
    ceil(alpha * (i + u)) with u in (0, 1); bin i spans
    [index(i-1), index(i))."""
    alpha = n_in / n_out
    idx = np.ceil(alpha * (np.arange(n_out + 1) + u)).astype(np.int64) - 1
    idx[0] = 0
    idx[-1] = n_in
    return idx


def _fractional_max(x, output_size, kernel_size, random_u, return_mask,
                    ndim, name):
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    spatial = xd.shape[2:]
    outs = _tuple(output_size, ndim)
    if random_u is None:
        # fresh u per eager call from the FRAMEWORK stream (paddle.seed
        # reproducible) — the stochastic regions ARE the regularizer
        # (Graham 2014). Note: under jit the draw happens at trace time,
        # so compiled steps reuse one u; pass random_u explicitly to
        # control it per step.
        from paddle_tpu.framework import random as _frng

        u = float(jax.random.uniform(_frng.next_key(), (),
                                     minval=1e-3, maxval=1 - 1e-3))
    else:
        u = float(random_u)
    if not (0 < u < 1):
        raise ValueError("random_u must be in (0, 1)")
    bounds = [_fractional_bounds(spatial[d], outs[d], u)
              for d in range(ndim)]
    kmax = [int(np.max(np.diff(b))) for b in bounds]
    if kernel_size is not None:
        ks = _tuple(kernel_size, ndim)
        kmax = [max(k, m) for k, m in zip(ks, kmax)]

    # gather each output bin's (padded-to-kmax) window and reduce: static
    # shapes, one fused gather+max per dim
    def pool_dim(v, d):
        b = bounds[d]
        starts = b[:-1]
        width = kmax[d]
        idx = starts[:, None] + np.arange(width)[None, :]
        valid = idx < b[1:, None]
        idx = np.minimum(idx, spatial[d] - 1)
        axis = 2 + d
        g = jnp.take(v, jnp.asarray(idx.reshape(-1)), axis=axis)
        new_shape = (v.shape[:axis] + (len(starts), width)
                     + v.shape[axis + 1:])
        g = g.reshape(new_shape)
        mask_shape = [1] * g.ndim
        mask_shape[axis], mask_shape[axis + 1] = len(starts), width
        m = jnp.asarray(valid).reshape(mask_shape)
        g = jnp.where(m, g, -jnp.inf)
        return jnp.max(g, axis=axis + 1)

    out = xd
    for d in range(ndim):
        out = pool_dim(out, d)
    out = out.astype(xd.dtype)
    if return_mask:
        raise NotImplementedError(
            "fractional_max_pool return_mask: use return_mask=False on "
            "this backend (the mask only feeds the legacy unpool path)")
    return Tensor(out)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Fractional max pooling (reference fractional_max_pool2d,
    `phi/kernels/.../fractional_max_pool2d_kernel`; Graham 2014): the
    pseudo-random bin boundaries come from `random_u` (deterministic for
    a given u, like the reference's seeded kernel)."""
    return _fractional_max(x, output_size, kernel_size, random_u,
                           return_mask, 2, name)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_max(x, output_size, kernel_size, random_u,
                           return_mask, 3, name)
