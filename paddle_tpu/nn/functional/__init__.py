"""paddle.nn.functional surface (reference: `python/paddle/nn/functional/__init__.py`)."""

from paddle_tpu.nn.functional.activation import *  # noqa: F401,F403
from paddle_tpu.nn.functional.common import *  # noqa: F401,F403
from paddle_tpu.nn.functional.conv import *  # noqa: F401,F403
from paddle_tpu.nn.functional.pooling import *  # noqa: F401,F403
from paddle_tpu.nn.functional.norm import *  # noqa: F401,F403
from paddle_tpu.nn.functional.loss import *  # noqa: F401,F403
from paddle_tpu.nn.functional.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attn_qkvpacked,
    flash_attn_unpadded,
    flash_attn_varlen_qkvpacked,
    scaled_dot_product_attention,
    sdp_kernel,
)
from paddle_tpu.nn.functional.extra_fns import *  # noqa: F401,F403,E402
