"""Normalization functionals (reference: `python/paddle/nn/functional/norm.py`).

rms_norm/fused paths mirror the reference's fused kernels
(`paddle/phi/kernels/fusion/gpu/fused_bias_dropout_residual_layer_norm_kernel.cu`,
`fused_rms_norm`); on TPU, XLA fuses these chains natively and the pallas
variants live in `paddle_tpu/kernels/`.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, apply


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))
    axes = tuple(range(-n_axes, 0))

    def fn(a, *wb):
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [t for t in (weight, bias) if t is not None]
    return apply(fn, x, *args, _name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    def fn(a, *w):
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1, keepdims=True)
        out = a.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype)
        if w:
            out = out * w[0]
        return out

    args = [weight] if weight is not None else []
    return apply(fn, x, *args, _name="rms_norm")


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None, name=None):
    cf = data_format.startswith("NC")
    ch_axis = 1 if (cf and x.ndim > 1) else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        # compute batch stats; update running stats in-place (host side-effect,
        # matches the reference's mutable mean/var outputs)
        mean = jnp.mean(x._data.astype(jnp.float32), axis=reduce_axes)
        var = jnp.var(x._data.astype(jnp.float32), axis=reduce_axes)
        if running_mean is not None:
            running_mean._data = (momentum * running_mean._data + (1.0 - momentum) * mean).astype(running_mean.dtype)
            n = x.size // x.shape[ch_axis]
            unbiased = var * (n / max(n - 1, 1))
            running_var._data = (momentum * running_var._data + (1.0 - momentum) * unbiased).astype(running_var.dtype)

        def fn(a, *wb):
            m = jnp.mean(a.astype(jnp.float32), axis=reduce_axes, keepdims=True)
            v = jnp.var(a.astype(jnp.float32), axis=reduce_axes, keepdims=True)
            out = (a.astype(jnp.float32) - m) * jax.lax.rsqrt(v + epsilon)
            out = out.astype(a.dtype)
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(shape)
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(shape)
            return out
    else:
        rm = running_mean._data.reshape(shape)
        rv = running_var._data.reshape(shape)

        def fn(a, *wb):
            out = (a - rm.astype(a.dtype)) * jax.lax.rsqrt(rv.astype(jnp.float32) + epsilon).astype(a.dtype)
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(shape)
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(shape)
            return out

    args = [t for t in (weight, bias) if t is not None]
    return apply(fn, x, *args, _name="batch_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else tuple(range(1, x.ndim - 1))
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    def fn(a, *wb):
        m = jnp.mean(a.astype(jnp.float32), axis=reduce_axes, keepdims=True)
        v = jnp.var(a.astype(jnp.float32), axis=reduce_axes, keepdims=True)
        out = ((a.astype(jnp.float32) - m) * jax.lax.rsqrt(v + eps)).astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [t for t in (weight, bias) if t is not None]
    return apply(fn, x, *args, _name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    cf = data_format.startswith("NC")
    ch_axis = 1 if cf else x.ndim - 1
    c = x.shape[ch_axis]
    shape = [1] * x.ndim
    shape[ch_axis] = c

    def fn(a, *wb):
        if cf:
            n = a.shape[0]
            g = a.reshape((n, num_groups, c // num_groups) + a.shape[2:])
            axes = tuple(range(2, g.ndim))
        else:
            n = a.shape[0]
            g = a.reshape((n,) + a.shape[1:-1] + (num_groups, c // num_groups))
            axes = tuple(range(1, g.ndim - 2)) + (g.ndim - 1,)
        m = jnp.mean(g.astype(jnp.float32), axis=axes, keepdims=True)
        v = jnp.var(g.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((g.astype(jnp.float32) - m) * jax.lax.rsqrt(v + epsilon)).astype(a.dtype)
        out = out.reshape(a.shape)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [t for t in (weight, bias) if t is not None]
    return apply(fn, x, *args, _name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1

    def fn(a):
        sq = jnp.square(a)
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[ch_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        window = [1] * a.ndim
        window[ch_axis] = size
        summed = jax.lax.reduce_window(padded, 0.0, jax.lax.add, tuple(window), (1,) * a.ndim, "VALID")
        return a / jnp.power(k + alpha * summed, beta)

    return apply(fn, x, _name="lrn")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(a):
        nrm = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=True), 1.0 / p)
        return a / jnp.maximum(nrm, epsilon)

    return apply(fn, x, _name="normalize")


def spectral_norm(weight, weight_u, weight_v, dim=0, power_iters=1, eps=1e-12, name=None):
    def fn(w, u, v):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        for _ in range(power_iters):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v
        return w / sigma

    return apply(fn, weight, weight_u, weight_v, _name="spectral_norm")
