"""Layer base class (reference: `python/paddle/nn/layer/layers.py:353` Layer,
with parameters/sublayers/buffers/hooks/state_dict semantics)."""

from __future__ import annotations

import collections

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework import dtypes


class Parameter(Tensor):
    """Trainable tensor (reference: `python/paddle/base/framework.py` EagerParamBase)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "do_model_average",
                 "need_clip", "is_distributed", "initializer", "_init_fn")

    def __init__(self, data, trainable=True, name=None):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True
        self.is_distributed = False
        self.persistable = True


class ParamAttr:
    """reference: `python/paddle/base/param_attr.py`"""

    def __init__(self, name=None, initializer=None, learning_rate=1.0, regularizer=None,
                 trainable=True, do_model_average=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        return ParamAttr(initializer=attr)


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


_name_counters = collections.defaultdict(int)


def _unique_name(prefix):
    _name_counters[prefix] += 1
    return f"{prefix}_{_name_counters[prefix] - 1}"


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names_set", set())
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._full_name = _unique_name(name_scope or self.__class__.__name__.lower())
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._casted_by_pure_fp16 = False

    # -- naming -------------------------------------------------------------
    def full_name(self):
        return self._full_name

    # -- parameter creation -------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from paddle_tpu.nn import initializer as I

        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype) or self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        data = init(shape, dtype)
        p = Parameter(data, trainable=attr.trainable, name=attr.name or _unique_name("param"))
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, dtype=None):
        return Tensor(jnp.zeros([], dtypes.convert_dtype(dtype) or self._dtype))

    # -- registration -------------------------------------------------------
    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        elif tensor is not None:
            tensor.persistable = True
        return tensor

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = []
        for store in ("_parameters", "_sub_layers", "_buffers"):
            extra += list(self.__dict__.get(store, {}).keys())
        return super().__dir__() + extra

    # -- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for layer_prefix, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (layer_prefix + "." + name if layer_prefix else name), p
            if not include_sublayers:
                break

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            p = prefix + "." + name if prefix else name
            yield from layer.named_sublayers(prefix=p, include_self=True)

    def children(self):
        return [l for _, l in self.named_children()]

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for layer_prefix, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for name, b in layer._buffers.items():
                if b is None:
                    continue
                yield (layer_prefix + "." + name if layer_prefix else name), b
            if not include_sublayers:
                break

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- modes --------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ---------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        return self._call_with_forward(self.forward, *inputs, **kwargs)

    def _call_with_forward(self, forward, *inputs, **kwargs):
        """__call__ semantics over an arbitrary forward implementation
        (dy2static substitutes a converted forward; hooks stay in force)."""
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            head = f"({name}): {rep[0]}"
            lines += [head] + ["  " + r for r in rep[1:]]
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n  " + "\n  ".join(lines) + "\n)"
        return main + ")"

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix="",
                   use_hook=True, keep_vars=True):
        dest = collections.OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            layer_name = name.rsplit(".", 1)[-1]
            owner = self
            if "." in name:
                for part in name.split(".")[:-1]:
                    owner = owner._sub_layers.get(part, owner)
            if layer_name not in getattr(owner, "_non_persistable_buffer_names_set", set()):
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                tgt = own[k]
                arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                if tuple(tgt.shape) != tuple(arr.shape):
                    raise ValueError(f"shape mismatch for {k}: {tgt.shape} vs {list(arr.shape)}")
                tgt._data = arr.astype(tgt.dtype)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype / device movement -------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._to_dtype(dtypes.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._to_dtype(dtypes.convert_dtype(dtype))
        return self

    def _to_dtype(self, dt, only_float=True):
        for _, p in self.named_parameters():
            if not only_float or dtypes.is_floating_point(p.dtype):
                p._data = p._data.astype(dt)
        for _, b in self.named_buffers():
            if not only_float or dtypes.is_floating_point(b.dtype):
                b._data = b._data.astype(dt)
        for l in self.sublayers(include_self=True):
            l._dtype = dt

    def float(self):
        self._to_dtype(jnp.float32)
        return self

    def half(self):
        self._to_dtype(jnp.float16)
        return self

    def bfloat16(self):
        self._to_dtype(jnp.bfloat16)
        return self

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()
