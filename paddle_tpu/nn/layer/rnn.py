"""RNN layers via lax.scan (reference: `python/paddle/nn/layer/rnn.py`).

Instead of the reference's per-timestep CUDA kernels / cuDNN RNN, recurrence
is expressed as `lax.scan`, which XLA compiles into a single fused loop on
TPU (no per-step dispatch overhead, weights stay in VMEM across steps).
"""

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, apply
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.nn import initializer as I


class _RNNBase(Layer):
    MODE = "RNN_TANH"
    GATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, activation="tanh", name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirect else 1
        self.activation = activation

        std = 1.0 / math.sqrt(hidden_size)
        gate = self.GATES
        for layer in range(num_layers):
            for direction in range(self.num_directions):
                suffix = "_reverse" if direction == 1 else ""
                in_size = input_size if layer == 0 else hidden_size * self.num_directions
                setattr(self, f"weight_ih_l{layer}{suffix}", self.create_parameter(
                    [gate * hidden_size, in_size], attr=weight_ih_attr,
                    default_initializer=I.Uniform(-std, std)))
                setattr(self, f"weight_hh_l{layer}{suffix}", self.create_parameter(
                    [gate * hidden_size, hidden_size], attr=weight_hh_attr,
                    default_initializer=I.Uniform(-std, std)))
                setattr(self, f"bias_ih_l{layer}{suffix}", self.create_parameter(
                    [gate * hidden_size], attr=bias_ih_attr, is_bias=True,
                    default_initializer=I.Uniform(-std, std)))
                setattr(self, f"bias_hh_l{layer}{suffix}", self.create_parameter(
                    [gate * hidden_size], attr=bias_hh_attr, is_bias=True,
                    default_initializer=I.Uniform(-std, std)))

    def _cell(self, mode):
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        if mode == "LSTM":
            def cell(carry, x_t, w_ih, w_hh, b_ih, b_hh):
                h, c = carry
                gates = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
                g = jnp.tanh(g)
                c = f * c + i * g
                h = o * jnp.tanh(c)
                return (h, c), h
        elif mode == "GRU":
            def cell(carry, x_t, w_ih, w_hh, b_ih, b_hh):
                h = carry[0]
                gi = x_t @ w_ih.T + b_ih
                gh = h @ w_hh.T + b_hh
                ir, iz, ig = jnp.split(gi, 3, axis=-1)
                hr, hz, hg = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                n = jnp.tanh(ig + r * hg)
                h = (1 - z) * n + z * h
                return (h,), h
        else:
            def cell(carry, x_t, w_ih, w_hh, b_ih, b_hh):
                h = carry[0]
                h = act(x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh)
                return (h,), h

        return cell

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self.MODE
        is_lstm = mode == "LSTM"
        n_state = 2 if is_lstm else 1
        cell = self._cell(mode)

        params = []
        for layer in range(self.num_layers):
            for direction in range(self.num_directions):
                suffix = "_reverse" if direction == 1 else ""
                params += [getattr(self, f"weight_ih_l{layer}{suffix}"),
                           getattr(self, f"weight_hh_l{layer}{suffix}"),
                           getattr(self, f"bias_ih_l{layer}{suffix}"),
                           getattr(self, f"bias_hh_l{layer}{suffix}")]

        time_major = self.time_major
        num_layers, num_directions = self.num_layers, self.num_directions
        hidden = self.hidden_size

        init_datas = []
        if initial_states is not None:
            states = initial_states if isinstance(initial_states, (list, tuple)) else [initial_states]
            init_datas = [s._data for s in states]

        def fn(x, *wparams):
            xs = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, I]
            b = xs.shape[1]
            if init_datas:
                h0 = init_datas[0]
                c0 = init_datas[1] if is_lstm else None
            else:
                h0 = jnp.zeros((num_layers * num_directions, b, hidden), xs.dtype)
                c0 = jnp.zeros_like(h0) if is_lstm else None

            out = xs
            final_h, final_c = [], []
            idx = 0
            for layer in range(num_layers):
                outs_dir = []
                for direction in range(num_directions):
                    w_ih, w_hh, b_ih, b_hh = wparams[idx:idx + 4]
                    idx += 4
                    sl = layer * num_directions + direction
                    carry0 = (h0[sl], c0[sl]) if is_lstm else (h0[sl],)
                    seq = out if direction == 0 else jnp.flip(out, 0)

                    def step(carry, x_t, _w=(w_ih, w_hh, b_ih, b_hh)):
                        return cell(carry, x_t, *_w)

                    carry, ys = jax.lax.scan(step, carry0, seq)
                    if direction == 1:
                        ys = jnp.flip(ys, 0)
                    outs_dir.append(ys)
                    final_h.append(carry[0])
                    if is_lstm:
                        final_c.append(carry[1])
                out = jnp.concatenate(outs_dir, axis=-1) if num_directions == 2 else outs_dir[0]
            out_final = out if time_major else jnp.swapaxes(out, 0, 1)
            hN = jnp.stack(final_h, 0)
            if is_lstm:
                cN = jnp.stack(final_c, 0)
                return out_final, hN, cN
            return out_final, hN

        results = apply(fn, inputs, *params, _name=f"rnn_{mode}")
        if is_lstm:
            out, hN, cN = results
            return out, (hN, cN)
        out, hN = results
        return out, hN


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"
    GATES = 1


class LSTM(_RNNBase):
    MODE = "LSTM"
    GATES = 4


class GRU(_RNNBase):
    MODE = "GRU"
    GATES = 3


class SimpleRNNCell(Layer):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.activation = activation
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter([hidden_size], is_bias=True,
                                             default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter([hidden_size], is_bias=True,
                                             default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        if states is None:
            import paddle_tpu as paddle

            states = paddle.zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)

        def fn(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out

        out = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                    self.bias_ih, self.bias_hh, _name="rnn_cell")
        return out, out


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter([4 * hidden_size], is_bias=True,
                                             default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter([4 * hidden_size], is_bias=True,
                                             default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            import paddle_tpu as paddle

            h = paddle.zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)
            c = paddle.zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)
        else:
            h, c = states

        def fn(x, h_, c_, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h_ @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            c_new = f * c_ + i * jnp.tanh(g)
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        h_new, c_new = apply(fn, inputs, h, c, self.weight_ih, self.weight_hh,
                             self.bias_ih, self.bias_hh, _name="lstm_cell")
        return h_new, (h_new, c_new)


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter([3 * hidden_size], is_bias=True,
                                             default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter([3 * hidden_size], is_bias=True,
                                             default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            import paddle_tpu as paddle

            states = paddle.zeros([inputs.shape[0], self.hidden_size], dtype=inputs.dtype)

        def fn(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ig = jnp.split(gi, 3, axis=-1)
            hr, hz, hg = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(ig + r * hg)
            return (1 - z) * n + z * h

        out = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                    self.bias_ih, self.bias_hh, _name="gru_cell")
        return out, out
