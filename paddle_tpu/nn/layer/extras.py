"""nn layer completion (r5 surface sweep): reference `python/paddle/nn/
__init__.py` members not covered elsewhere — thin Layer wrappers over the
functional forms, RNN cell runners, and seq2seq decoding
(`python/paddle/nn/decode.py`)."""

from __future__ import annotations

import jax.numpy as jnp
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

__all__ = [
    "Silu", "Softmax2D", "PairwiseDistance", "Unflatten", "ZeroPad1D",
    "ZeroPad3D", "FractionalMaxPool2D", "FractionalMaxPool3D", "LPPool1D",
    "LPPool2D", "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "FeatureAlphaDropout", "GaussianNLLLoss", "PoissonNLLLoss",
    "SoftMarginLoss", "MultiLabelSoftMarginLoss", "MultiMarginLoss",
    "TripletMarginWithDistanceLoss", "RNNTLoss", "HSigmoidLoss",
    "AdaptiveLogSoftmaxWithLoss", "ParameterDict", "RNNCellBase", "RNN",
    "BiRNN", "BeamSearchDecoder", "dynamic_decode",
]


class Silu(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        from paddle_tpu.nn import functional as F

        return F.silu(x)


class Softmax2D(Layer):
    """softmax over the channel dim of NCHW input (reference nn.Softmax2D)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        from paddle_tpu.nn import functional as F

        if x.ndim not in (3, 4):
            raise ValueError(
                f"Softmax2D expects 3D/4D input, got {x.ndim}D")
        return F.softmax(x, axis=-3)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from paddle_tpu.nn import functional as F

        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        import paddle_tpu as paddle

        return paddle.unflatten(x, self.axis, self.shape)


class _ZeroPadN(Layer):
    _NDIM = None

    def __init__(self, padding, data_format=None, name=None):
        super().__init__()
        if isinstance(padding, int):
            padding = [padding] * (2 * self._NDIM)
        self.padding = list(padding)
        self.data_format = data_format

    def forward(self, x):
        from paddle_tpu.nn import functional as F

        return F.pad(x, self.padding, mode="constant", value=0.0,
                     data_format=self.data_format or
                     ("NCL" if self._NDIM == 1 else "NCDHW"))


class ZeroPad1D(_ZeroPadN):
    _NDIM = 1


class ZeroPad3D(_ZeroPadN):
    _NDIM = 3


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.kw = dict(output_size=output_size, kernel_size=kernel_size,
                       random_u=random_u, return_mask=return_mask)

    def forward(self, x):
        from paddle_tpu.nn import functional as F

        return F.fractional_max_pool2d(x, **self.kw)


class FractionalMaxPool3D(FractionalMaxPool2D):
    def forward(self, x):
        from paddle_tpu.nn import functional as F

        return F.fractional_max_pool3d(x, **self.kw)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        from paddle_tpu.nn import functional as F

        return F.lp_pool1d(x, *self.args)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        from paddle_tpu.nn import functional as F

        return F.lp_pool2d(x, *self.args)


class _MaxUnPoolN(Layer):
    _FN = None

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self.kw = dict(kernel_size=kernel_size, stride=stride,
                       padding=padding, output_size=output_size)
        if data_format is not None:
            self.kw["data_format"] = data_format

    def forward(self, x, indices):
        from paddle_tpu.nn import functional as F

        return getattr(F, self._FN)(x, indices, **self.kw)


class MaxUnPool1D(_MaxUnPoolN):
    _FN = "max_unpool1d"


class MaxUnPool2D(_MaxUnPoolN):
    _FN = "max_unpool2d"


class MaxUnPool3D(_MaxUnPoolN):
    _FN = "max_unpool3d"


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        from paddle_tpu.nn import functional as F

        return F.feature_alpha_dropout(x, self.p, training=self.training)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        from paddle_tpu.nn import functional as F

        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.kw = dict(log_input=log_input, full=full, epsilon=epsilon,
                       reduction=reduction)

    def forward(self, input, label):
        from paddle_tpu.nn import functional as F

        return F.poisson_nll_loss(input, label, **self.kw)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        from paddle_tpu.nn import functional as F

        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        from paddle_tpu.nn import functional as F

        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.kw = dict(p=p, margin=margin, weight=weight, reduction=reduction)

    def forward(self, input, label):
        from paddle_tpu.nn import functional as F

        return F.multi_margin_loss(input, label, **self.kw)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.kw = dict(distance_function=distance_function, margin=margin,
                       swap=swap, reduction=reduction)

    def forward(self, input, positive, negative):
        from paddle_tpu.nn import functional as F

        return F.triplet_margin_with_distance_loss(
            input, positive, negative, **self.kw)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank, self.lam, self.reduction = blank, fastemit_lambda, reduction

    def forward(self, logits, labels, logit_lengths, label_lengths):
        from paddle_tpu.nn import functional as F

        return F.rnnt_loss(logits, labels, logit_lengths, label_lengths,
                           blank=self.blank, fastemit_lambda=self.lam,
                           reduction=self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid loss layer (reference nn.HSigmoidLoss):
    owns the tree weight/bias and delegates to F.hsigmoid_loss."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if not is_custom and num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self.num_classes = num_classes
        self.is_custom = is_custom
        import math

        from paddle_tpu.nn import initializer as I

        rows = num_classes - 1 if not is_custom else num_classes
        std = 1.0 / math.sqrt(feature_size)
        self.weight = self.create_parameter(
            [rows, feature_size], attr=weight_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias = self.create_parameter(
            [rows, 1], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, input, label, path_table=None, path_code=None):
        from paddle_tpu.nn import functional as F

        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table=path_table,
                               path_code=path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """reference nn.AdaptiveLogSoftmaxWithLoss: owns head + tail
    projections; cutoffs EXCLUDES n_classes (appended internally)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if (cutoffs != sorted(cutoffs) or min(cutoffs) <= 0
                or max(cutoffs) > n_classes - 1
                or len(set(cutoffs)) != len(cutoffs)):
            raise ValueError(
                "cutoffs must be a sorted list of unique positive ints "
                "< n_classes")
        self.cutoffs = cutoffs + [n_classes]
        self.n_clusters = len(self.cutoffs) - 1
        shortlist = self.cutoffs[0]
        from paddle_tpu.nn import initializer as I

        self.head_weight = self.create_parameter(
            [in_features, shortlist + self.n_clusters],
            default_initializer=I.XavierUniform())
        self.head_bias = self.create_parameter(
            [shortlist + self.n_clusters], is_bias=True,
            default_initializer=I.Constant(0.0)) if head_bias else None
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features / (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            w1 = self.create_parameter([in_features, hsz],
                                       default_initializer=I.XavierUniform())
            w2 = self.create_parameter([hsz, osz],
                                       default_initializer=I.XavierUniform())
            setattr(self, f"_tail_{i}_0", w1)
            setattr(self, f"_tail_{i}_1", w2)
            self.tail_weights.append((w1, w2))

    def forward(self, input, label):
        from paddle_tpu.nn import functional as F

        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights, self.cutoffs,
            head_bias=self.head_bias)

    def log_prob(self, input):
        """Full [N, n_classes] log-probabilities."""
        import jax

        x = input._data if isinstance(input, Tensor) else jnp.asarray(input)
        hw = self.head_weight._data
        head = x @ hw + (self.head_bias._data
                         if self.head_bias is not None else 0.0)
        hlp = jax.nn.log_softmax(head, axis=-1)
        shortlist = self.cutoffs[0]
        parts = [hlp[:, :shortlist]]
        for i, (w1, w2) in enumerate(self.tail_weights):
            tl = jax.nn.log_softmax((x @ w1._data) @ w2._data, axis=-1)
            parts.append(hlp[:, shortlist + i:shortlist + i + 1] + tl)
        return Tensor(jnp.concatenate(parts, axis=1))

    def predict(self, input):
        return Tensor(jnp.argmax(self.log_prob(input)._data, axis=1))


class ParameterDict(Layer):
    """dict-style parameter container (reference nn.ParameterDict)."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            items = parameters.items() if hasattr(parameters, "items") \
                else parameters
            for k, v in items:
                self.add_parameter(str(k), v)

    def __getitem__(self, key):
        return self._parameters[str(key)]

    def __setitem__(self, key, param):
        self.add_parameter(str(key), param)

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def __contains__(self, key):
        return str(key) in self._parameters

    def keys(self):
        return self._parameters.keys()

    def values(self):
        return self._parameters.values()

    def items(self):
        return self._parameters.items()

    def update(self, parameters):
        items = parameters.items() if hasattr(parameters, "items") \
            else parameters
        for k, v in items:
            self.add_parameter(str(k), v)


class RNNCellBase(Layer):
    """Base for user-defined recurrent cells (reference
    `python/paddle/nn/layer/rnn.py` RNNCellBase): provides
    get_initial_states for RNN/BiRNN/decoders."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        import paddle_tpu as paddle

        batch = batch_ref.shape[batch_dim_idx]
        shape = shape if shape is not None else self.state_shape

        def build(s):
            if isinstance(s, (list, tuple)) and s and isinstance(
                    s[0], (list, tuple)):
                return type(s)(build(x) for x in s)
            dims = [batch] + [int(d) for d in s]
            return paddle.full(dims, init_value,
                               dtype=dtype or batch_ref.dtype)

        if isinstance(shape, (list, tuple)) and shape and isinstance(
                shape[0], (list, tuple)):
            return type(shape)(build(s) for s in shape)
        return build(shape)

    @property
    def state_shape(self):
        raise NotImplementedError(
            "cells must define state_shape to use get_initial_states")


def _run_cell(cell, inputs, initial_states, time_major, reverse=False,
              sequence_length=None):
    """Unroll a cell over time in eager mode. sequence_length freezes
    states past each sample's length (reference RNN mask semantics)."""
    import paddle_tpu as paddle

    axis = 0 if time_major else 1
    T = inputs.shape[axis]
    steps = range(T - 1, -1, -1) if reverse else range(T)
    states = initial_states
    outs = [None] * T
    seq = None
    if sequence_length is not None:
        seq = sequence_length._data if isinstance(sequence_length, Tensor) \
            else jnp.asarray(sequence_length)
    for t in steps:
        x_t = inputs[:, t] if axis == 1 else inputs[t]
        out, new_states = cell(x_t, states)
        if seq is not None:
            alive = Tensor((t < seq).astype(out._data.dtype)[:, None])
            out = out * alive
            if isinstance(new_states, (tuple, list)):
                new_states = type(new_states)(
                    n * alive + s * (1.0 - alive)
                    for n, s in zip(new_states, states))
            else:
                new_states = new_states * alive + states * (1.0 - alive)
        outs[t] = out
        states = new_states
    stacked = paddle.stack(outs, axis=axis)
    return stacked, states


class RNN(Layer):
    """Runs any cell over a sequence (reference nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            if hasattr(self.cell, "get_initial_states"):
                initial_states = self.cell.get_initial_states(
                    inputs, batch_dim_idx=1 if self.time_major else 0)
            else:
                out, initial_states = self.cell(
                    inputs[0] if self.time_major else inputs[:, 0], None)
                import jax.tree_util as jtu

                initial_states = jtu.tree_map(
                    lambda s: s * 0.0, initial_states,
                    is_leaf=lambda x: isinstance(x, Tensor))
        return _run_cell(self.cell, inputs, initial_states, self.time_major,
                         reverse=self.is_reverse,
                         sequence_length=sequence_length)


class BiRNN(Layer):
    """Forward + backward cells over one sequence (reference nn.BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw, self.cell_bw = cell_fw, cell_bw
        self.time_major = time_major
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        import paddle_tpu as paddle

        fw_init = bw_init = None
        if initial_states is not None:
            fw_init, bw_init = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, fw_init, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, bw_init, sequence_length)
        return paddle.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class BeamSearchDecoder:
    """Beam-search decoding over a cell (reference
    `python/paddle/nn/decode.py:BeamSearchDecoder`): scores are summed
    log-probs; finished beams are frozen with end_token."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        d = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        d = jnp.repeat(d[:, None], beam_size, axis=1)
        return Tensor(d.reshape((-1,) + d.shape[2:]))


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Decode until all beams finish or max_step_num (reference
    `python/paddle/nn/decode.py:dynamic_decode`). Eager loop — decoding is
    autoregressive and data-dependent; each cell step is still jit-compiled
    through the op layer."""
    import jax
    import jax.tree_util as jtu

    cell = decoder.cell
    K = decoder.beam_size
    max_steps = int(max_step_num or 32)

    if inits is None:
        raise ValueError(
            "dynamic_decode requires inits (the cell's initial states, "
            "e.g. paddle.zeros([batch, hidden])) — the batch size cannot "
            "be inferred without them")
    states = inits

    # per-(batch*beam) running state
    def _tile(s):
        d = s._data if isinstance(s, Tensor) else jnp.asarray(s)
        d = jnp.repeat(d[:, None], K, axis=1)
        return Tensor(d.reshape((-1,) + d.shape[2:]))

    states = jtu.tree_map(_tile, states,
                          is_leaf=lambda x: isinstance(x, Tensor))
    probe = jtu.tree_leaves(
        states, is_leaf=lambda x: isinstance(x, Tensor))[0]
    BK = probe.shape[0]
    B = BK // K
    scores = jnp.tile(jnp.array([0.0] + [-1e9] * (K - 1)), (B,))  # [B*K]
    tokens = jnp.full((BK,), decoder.start_token, jnp.int32)
    finished = jnp.zeros((BK,), bool)
    collected = []
    lengths = jnp.zeros((BK,), jnp.int32)
    for step in range(max_steps):
        emb = decoder.embedding_fn(Tensor(tokens)) if decoder.embedding_fn \
            else Tensor(jax.nn.one_hot(tokens, probe.shape[-1]))
        out, new_states = cell(emb, states)
        logits = decoder.output_fn(out) if decoder.output_fn else out
        lp = jax.nn.log_softmax(
            logits._data if isinstance(logits, Tensor) else logits, axis=-1)
        V = lp.shape[-1]
        # frozen beams only extend with end_token at zero cost
        frozen = jnp.full((V,), -1e9).at[decoder.end_token].set(0.0)
        lp = jnp.where(finished[:, None], frozen[None, :], lp)
        total = scores[:, None] + lp                      # [B*K, V]
        flat = total.reshape(B, K * V)
        top_v, top_i = jax.lax.top_k(flat, K)             # [B, K]
        beam_src = top_i // V                             # [B, K]
        tok = (top_i % V).astype(jnp.int32)
        gidx = (jnp.arange(B)[:, None] * K + beam_src).reshape(-1)
        scores = top_v.reshape(-1)
        tokens = tok.reshape(-1)
        finished = finished[gidx] | (tokens == decoder.end_token)
        lengths = jnp.where(finished, lengths[gidx], lengths[gidx] + 1)
        states = jtu.tree_map(
            lambda s: Tensor(s._data[gidx] if isinstance(s, Tensor)
                             else jnp.asarray(s)[gidx]),
            new_states, is_leaf=lambda x: isinstance(x, Tensor))
        # re-point already-collected history at the surviving beams
        collected = [c[gidx] for c in collected]
        collected.append(tokens)
        if bool(finished.all()):
            break
    ids = jnp.stack(collected, axis=1).reshape(B, K, -1)  # [B, K, T]
    if output_time_major:
        ids = jnp.moveaxis(ids, -1, 0)
    out = (Tensor(ids), Tensor(scores.reshape(B, K)))
    if return_length:
        return out + (Tensor(lengths.reshape(B, K)),)
    return out
