"""Activation layers (reference: `python/paddle/nn/layer/activation.py`)."""

from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I


def _mk(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = {**defaults}
            keys = list(defaults.keys())
            for i, a in enumerate(args):
                self._kwargs[keys[i]] = a
            for k, v in kwargs.items():
                if k in self._kwargs:
                    self._kwargs[k] = v

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _mk("ReLU", F.relu)
ReLU6 = _mk("ReLU6", F.relu6)
Sigmoid = _mk("Sigmoid", F.sigmoid)
Tanh = _mk("Tanh", F.tanh)
GELU = _mk("GELU", F.gelu, approximate=False)
SiLU = _mk("SiLU", F.silu)
Swish = _mk("Swish", F.silu)
Mish = _mk("Mish", F.mish)
LeakyReLU = _mk("LeakyReLU", F.leaky_relu, negative_slope=0.01)
ELU = _mk("ELU", F.elu, alpha=1.0)
SELU = _mk("SELU", F.selu)
CELU = _mk("CELU", F.celu, alpha=1.0)
Hardtanh = _mk("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
Hardshrink = _mk("Hardshrink", F.hardshrink, threshold=0.5)
Softshrink = _mk("Softshrink", F.softshrink, threshold=0.5)
Tanhshrink = _mk("Tanhshrink", F.tanhshrink)
Hardsigmoid = _mk("Hardsigmoid", F.hardsigmoid)
Hardswish = _mk("Hardswish", F.hardswish)
Softplus = _mk("Softplus", F.softplus, beta=1, threshold=20)
Softsign = _mk("Softsign", F.softsign)
LogSigmoid = _mk("LogSigmoid", F.log_sigmoid)
Softmax = _mk("Softmax", F.softmax, axis=-1)
LogSoftmax = _mk("LogSoftmax", F.log_softmax, axis=-1)
ThresholdedReLU = _mk("ThresholdedReLU", F.thresholded_relu, threshold=1.0)
Maxout = _mk("Maxout", F.maxout, groups=2, axis=1)
GLU = _mk("GLU", F.glu, axis=-1)
RReLU = _mk("RReLU", F.rrelu, lower=1.0 / 8.0, upper=1.0 / 3.0)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr, default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
