"""Static analysis for compiled programs (the framework's self-check layer).

The reference framework ships an entire static layer of runtime
self-checks (the `PHI_DEFINE_EXPORTED_*` flag census, FLAGS_check_nan_inf,
accuracy-compare tooling). paddle_tpu's equivalents are *invariants on the
compiled program itself* — no [b, s, vocab] buffer in the fused-CE step,
opt state donated, exactly one psum per row-parallel matmul — and this
package checks them statically: trace the real program, walk its jaxpr /
lowered MLIR, and fail loudly (with eqn provenance) when an invariant
breaks. Everything here runs at test time, on CPU, in seconds; nothing
waits for a bench run to notice a regression.

Layout:
  jaxpr_walk        reusable jaxpr walker (scan/cond/custom_vjp/shard_map
                    subjaxprs, source_info provenance)
  buffer_audit      largest intermediates, byte ceilings, forbidden shapes
  donation_audit    input-output aliasing of donated args in lowered MLIR
  dtype_audit       f32 dot_generals under a bf16 policy (allowlisted sites)
  host_sync_audit   callbacks / infeed in step programs
  collective_audit  psum census + fingerprint per shard_map program
  programs          builders that trace the REAL program families at toy
                    size (train step, paged serving steps, fused CE,
                    optimizer write-back)
  presets           the default audit suite `tools/lint.py` runs in CI

See ARCHITECTURE.md "Static analysis" for the rule inventory and how to
add a rule.
"""

from paddle_tpu.analysis.base import Violation  # noqa: F401
from paddle_tpu.analysis import (  # noqa: F401
    buffer_audit,
    collective_audit,
    donation_audit,
    dtype_audit,
    host_sync_audit,
    jaxpr_walk,
)

__all__ = [
    "Violation",
    "jaxpr_walk",
    "buffer_audit",
    "donation_audit",
    "dtype_audit",
    "host_sync_audit",
    "collective_audit",
]
