"""Builders that trace the REAL program families at toy size.

The audit's whole value is that it inspects the programs production
actually runs — not idealized stand-ins. Each builder here constructs
the genuine code path (HybridParallelEngine.build_train_step, the
PagedEngine's compiled step dict, fused_linear_cross_entropy,
adamw_update) at a CPU-friendly toy size and returns `AuditProgram`
records carrying the jaxpr (for walker rules) and the lowered MLIR (for
the donation rule).

Serving programs are captured, not reconstructed: the engine's jitted
step callables are wrapped with a recorder, a couple of tiny requests
are served, and the recorded example arguments re-trace the exact
program objects the scheduler dispatched. A signature change in the
engine therefore can't silently diverge from what the audit inspects.

Everything is memoized per process — tests and tools/lint.py share one
build.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["AuditProgram", "TOY", "toy_args", "fused_ce_programs",
           "train_step_program", "opt_writeback_program",
           "serving_programs", "disagg_programs"]

# one toy geometry for every family: 2 layers, divisible by a degree-2
# TP mesh (heads, kv heads, intermediate), tiny enough that every build
# in this module traces in seconds on CPU. intermediate_size must NOT
# equal vocab_size or the forbidden-(b,s,vocab) probe would false-flag
# the MLP intermediates.
TOY = dict(vocab_size=64, hidden_size=32, intermediate_size=48,
           num_layers=2, num_heads=2, num_kv_heads=2)
TOY_BATCH, TOY_SEQ, TOY_CHUNK = 2, 16, 8


@dataclasses.dataclass
class AuditProgram:
    """One traced program, ready for rules: jaxpr for walker rules,
    lowered MLIR text + example args + donated argnums for the donation
    rule, meta for program-specific context (forbidden shapes, mesh)."""

    name: str
    jaxpr: object                       # ClosedJaxpr
    lowered_text: str | None = None
    example_args: tuple = ()
    donated: tuple = ()
    # kept_var_idx of the lowering (None = no pruning) and, for SPMD
    # programs, the compiled-HLO text where the resolved aliases live
    kept: frozenset | None = None
    compiled_text: str | None = None
    meta: dict = dataclasses.field(default_factory=dict)


def toy_args(**overrides):
    from paddle_tpu.models import llama_functional as lf

    kw = dict(TOY, **overrides)
    return lf.LlamaArgs(rope_theta=10000.0, rms_eps=1e-6, use_flash=False,
                        **kw)


def _sds(x):
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))


def _sds_tree(tree):
    return jax.tree_util.tree_map(_sds, tree)


def _from_traced(name, traced, example_args, donated, meta=None):
    """AuditProgram from a jit Traced: jaxpr + lowered MLIR, plus the
    lowering's kept_var_idx (unused-arg pruning shifts flat indices) and
    — when donation is requested but the StableHLO only carries the
    jax.buffer_donor mark (SPMD lowerings) — the compiled HLO text,
    where the resolved input_output_alias header lives."""
    lowered = traced.lower()
    text = lowered.as_text()
    kept = None
    try:
        kv = lowered._lowering.compile_args.get("kept_var_idx")
        if kv is not None:
            kept = frozenset(kv)
    except AttributeError:
        pass
    compiled_text = None
    if donated and "tf.aliasing_output" not in text:
        compiled_text = lowered.compile().as_text()
    return AuditProgram(
        name, traced.jaxpr, lowered_text=text, example_args=example_args,
        donated=donated, kept=kept, compiled_text=compiled_text,
        meta=dict(meta or {}))


class _Recorder:
    """Wrap a jitted callable; record the first call's args as
    ShapeDtypeStructs so the exact program can be re-traced for audit.
    Keyword args (the engines only pass static ones, e.g. the GPT
    programs' `sample=`) are kept verbatim and replayed at trace time."""

    def __init__(self, jitted):
        self.jitted = jitted
        self.args = None
        self.kwargs = {}

    def __call__(self, *a, **k):
        if self.args is None:
            self.args = tuple(_sds_tree(x) for x in a)
            self.kwargs = dict(k)
        return self.jitted(*a, **k)

    def trace(self):
        if self.args is None:
            return None
        return self.jitted.trace(*self.args, **self.kwargs)


@functools.lru_cache(maxsize=None)
def fused_ce_programs():
    """Fused-CE fwd+bwd (the no-[b,s,vocab] family) AND the unchunked
    reference — the reference is the teeth check: it MUST trip the
    forbidden-shape rule or the probe has silently gone blind."""
    from paddle_tpu.models import llama_functional as lf

    args = toy_args()
    b, s, chunk = TOY_BATCH, TOY_SEQ, TOY_CHUNK
    kh, kw, kl = jax.random.split(jax.random.PRNGKey(0), 3)
    h = jax.random.normal(kh, (b, s, args.hidden_size)) * 0.5
    head = jax.random.normal(kw, (args.hidden_size, args.vocab_size)) * 0.05
    labels = jax.random.randint(kl, (b, s), 0, args.vocab_size)

    fused = jax.make_jaxpr(jax.value_and_grad(
        lambda a, w: lf.fused_linear_cross_entropy(
            a, w, labels, args, None, 1, chunk), argnums=(0, 1)))(h, head)
    ref = jax.make_jaxpr(jax.value_and_grad(
        lambda a, w: lf.parallel_cross_entropy(a @ w, labels, args,
                                               None, 1),
        argnums=(0, 1)))(h, head)
    bsv = (b, s, args.vocab_size)
    return (AuditProgram("fused_ce_fwd_bwd", fused,
                         meta={"forbidden_shape": bsv}),
            AuditProgram("unchunked_ce_reference", ref,
                         meta={"forbidden_shape": bsv}))


@functools.lru_cache(maxsize=None)
def train_step_program(dtype_name="bfloat16"):
    """The hybrid engine's REAL compiled train step (trivial 1x1x1 mesh —
    the degenerate-mesh fast path), bf16 params, chunked fused-CE loss,
    bf16 moments + f32 master weights: the program the MFU headline runs.
    Donates params and opt state (argnums 0, 1)."""
    from paddle_tpu.distributed.hybrid_engine import HybridParallelEngine
    from paddle_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny(
        vocab_size=TOY["vocab_size"], hidden_size=TOY["hidden_size"],
        intermediate_size=TOY["intermediate_size"],
        num_hidden_layers=TOY["num_layers"],
        num_attention_heads=TOY["num_heads"],
        num_key_value_heads=TOY["num_kv_heads"],
        max_position_embeddings=TOY_SEQ, use_flash_attention=False)
    eng = HybridParallelEngine(
        cfg, dp=1, pp=1, mp=1, micro_batches=1,
        dtype=jnp.dtype(dtype_name), remat=False,
        loss_chunk=TOY_CHUNK, moments="bf16", master_weights=True)
    params, opt = eng.init_state(0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, TOY["vocab_size"],
                       (TOY_BATCH, TOY_SEQ)).astype(np.int32)
    labels = rng.integers(0, TOY["vocab_size"],
                          (TOY_BATCH, TOY_SEQ)).astype(np.int32)
    ids, labels = eng.shard_batch(ids, labels)
    step = eng.build_train_step()
    traced = step.trace(params, opt, ids, labels)
    example = (_sds_tree(params), _sds_tree(opt), _sds_tree(ids),
               _sds_tree(labels))
    return _from_traced(
        "hybrid_train_step", traced, example, donated=(0, 1),
        meta={"policy": ("bf16" if dtype_name == "bfloat16" else "f32"),
              "forbidden_shape": (TOY_BATCH, TOY_SEQ, TOY["vocab_size"])})


@functools.lru_cache(maxsize=None)
def opt_writeback_program(moments="bf16"):
    """The fused optimizer write-back on its own: one jitted tree-level
    adamw_update with donated params + opt state — the no-double-buffered
    -HBM contract for the optimizer family."""
    from paddle_tpu.distributed.hybrid_engine import adamw_init, adamw_update
    from paddle_tpu.models import llama_functional as lf

    # master_weights=False here: with masters on, adamw_update never
    # reads the raw params (only their static dtype), jit prunes them
    # from the lowering, and the flat-arg mapping breaks. The
    # master-weights donation path is covered by train_step_program,
    # where params feed the forward pass and survive pruning.
    args = toy_args()
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16),
        lf.init_params(args, jax.random.key(0)))
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    state = adamw_init(params, moments=moments, master_weights=False)
    step = jax.jit(functools.partial(adamw_update, moments=moments),
                   donate_argnums=(0, 2))
    traced = step.trace(params, grads, state)
    example = (_sds_tree(params), _sds_tree(grads), _sds_tree(state))
    return _from_traced("fused_opt_writeback", traced, example,
                        donated=(0, 2), meta={"policy": "bf16"})


def _tp_mesh(degree=2):
    from jax.sharding import Mesh

    if len(jax.devices()) < degree:
        return None
    return Mesh(np.array(jax.devices()[:degree]), ("mp",))


@functools.lru_cache(maxsize=None)
def serving_programs(tp=2, num_heads=None):
    """Capture the PagedEngine's real step programs by serving tiny
    requests through two engines (plain TP: prefill/decode/COW page-copy;
    TP + draft: the speculative verify), then re-tracing the captured
    callables. tp=0 builds without a mesh (single-chip program shapes).
    `num_heads` widens the toy head count when tp exceeds TOY's 2 heads
    (the deep -m slow audits run tp=4).

    Returns {name: AuditProgram}. The pool (pk/pv) argnums each program
    donates ride in `donated`; meta carries the mesh degree and layer
    count for the collective-census formula."""
    from paddle_tpu.models import generation as gen
    from paddle_tpu.models import llama_functional as lf
    from paddle_tpu.serving import PagedEngine, Request

    overrides = ({"num_heads": num_heads, "num_kv_heads": num_heads}
                 if num_heads else {})
    args = toy_args(**overrides)
    params = lf.init_params(args, jax.random.key(0))
    mesh = _tp_mesh(tp) if tp else None
    if tp and mesh is None:
        raise RuntimeError(
            f"serving_programs(tp={tp}) needs >= {tp} devices "
            f"(have {len(jax.devices())}); run under the virtual CPU mesh")
    kw = dict(max_slots=2, max_len=32, page_size=8, min_bucket=8,
              donate_steps=True, mesh=mesh)
    rng = np.random.default_rng(7)

    def prompt(n):
        return rng.integers(1, args.vocab_size, size=n).astype(np.int32)

    out = {}
    meta = {"tp": tp if mesh is not None else 0,
            "num_layers": args.num_layers}

    # plain engine: prefill + decode captured by serving; the COW
    # page-copy program never fires on the natural flow (the allocator
    # only COWs shared/registered tail pages), so it is traced directly
    # from the engine's own jitted object with the live pool shapes
    eng = PagedEngine(params, args, **kw)
    recs = {
        "paged_prefill": _Recorder(eng._prefill_v[False]),
        "paged_decode": _Recorder(eng._decode_v[False]),
    }
    eng._prefill_v[False] = recs["paged_prefill"]
    eng._decode_v[False] = recs["paged_decode"]
    eng.serve([Request(prompt(16), max_new_tokens=4),
               Request(prompt(10), max_new_tokens=3)])
    i32 = jax.ShapeDtypeStruct((), jnp.int32)
    copy_args = (_sds_tree(eng._pk), _sds_tree(eng._pv), i32, i32)
    out["page_copy"] = _from_traced(
        "page_copy", eng._copy_page.trace(*copy_args), copy_args,
        donated=(0, 1), meta=meta)
    donated = {"paged_prefill": (6, 7), "paged_decode": (2, 3)}

    # int8-pool engine: the same step family over QuantizedKVPage pools
    # (int8 codes + per-(page, kv-head) scales). Quantize-at-scatter and
    # dequant-at-gather must not change the collective structure (still
    # the 2 row-parallel psums per scanned layer body), and the int8
    # page copy must stay pure data movement over BOTH leaves.
    eng8 = PagedEngine(params, args, kv_dtype="int8", **kw)
    recs8 = {
        "paged_prefill_int8": _Recorder(eng8._prefill_v[False]),
        "paged_decode_int8": _Recorder(eng8._decode_v[False]),
    }
    eng8._prefill_v[False] = recs8["paged_prefill_int8"]
    eng8._decode_v[False] = recs8["paged_decode_int8"]
    eng8.serve([Request(prompt(16), max_new_tokens=4),
                Request(prompt(10), max_new_tokens=3)])
    copy8 = (_sds_tree(eng8._pk), _sds_tree(eng8._pv), i32, i32)
    out["page_copy_int8"] = _from_traced(
        "page_copy_int8", eng8._copy_page.trace(*copy8), copy8,
        donated=(0, 1), meta=meta)
    recs.update(recs8)
    donated["paged_prefill_int8"] = (6, 7)
    donated["paged_decode_int8"] = (2, 3)

    # draft engine: the speculative verify program (plain decode is
    # replaced by propose/verify rounds when a draft is loaded)
    draft_params, draft_args = gen.draft_from_params(params, args,
                                                     num_layers=1)
    spec = PagedEngine(params, args, draft_params=draft_params,
                       draft_args=draft_args, spec_tokens=2, **kw)
    recs["spec_verify"] = _Recorder(spec._spec._verify)
    spec._spec._verify = recs["spec_verify"]
    spec.serve([Request(prompt(9), max_new_tokens=4)])
    donated["spec_verify"] = (2, 3)

    for name, rec in recs.items():
        traced = rec.trace()
        if traced is None:
            continue  # program never dispatched (scheduler change?)
        out[name] = _from_traced(name, traced, rec.args,
                                 donated=donated[name], meta=meta)
    return out


@functools.lru_cache(maxsize=None)
def disagg_programs():
    """Capture the disaggregated-serving + router device programs by
    migrating tiny requests end-to-end (prefill worker -> LocalTransport
    -> decode worker, model-dtype AND int8 pools) and serving a couple
    of GPT requests through the router's `GptEngine`:

      page_extract[/._int8]   the prefill side's pool gather (never
                              donates — the pool must survive the ship)
      page_scatter[/_int8]    the decode side's write of shipped page
                              contents into fresh pages (donates both
                              pool trees, like every other step program)
      gpt_prefill/gpt_decode  the second autoregressive model family on
                              the stripe scheduler (learned positions,
                              donated KV stripes)

    All six are single-chip programs; the migration pair is pinned
    collective-free (pure data movement) — on a TP mesh the pool leaves
    are sharded on the kv-head axis and extract/scatter still never
    cross shards. Returns {name: AuditProgram}."""
    from paddle_tpu.serving import PagedEngine, Request  # noqa: F401
    from paddle_tpu.serving.disagg import (DecodeWorker, LocalTransport,
                                           PrefillWorker)
    from paddle_tpu.serving.router import GptEngine
    from paddle_tpu.models import llama_functional as lf

    args = toy_args()
    params = lf.init_params(args, jax.random.key(0))
    kw = dict(max_slots=2, max_len=32, page_size=8, min_bucket=8,
              donate_steps=True)
    rng = np.random.default_rng(11)

    def prompt(n, vocab=args.vocab_size):
        return rng.integers(1, vocab, size=n).astype(np.int32)

    recs, donated = {}, {}
    meta = {"tp": 0, "num_layers": args.num_layers}

    def migrate(kv_dtype, suffix):
        lt = LocalTransport()
        pw = PrefillWorker(params, args, transport=lt,
                           kv_dtype=kv_dtype, **kw)
        done = []
        dw = DecodeWorker(params, args, transport=lt, kv_dtype=kv_dtype,
                          completion_cb=done.append, **kw)
        recs[f"page_extract{suffix}"] = pw._page_extract = _Recorder(
            pw._page_extract)
        recs[f"page_scatter{suffix}"] = dw._page_scatter = _Recorder(
            dw._page_scatter)
        donated[f"page_extract{suffix}"] = ()
        donated[f"page_scatter{suffix}"] = (0, 1)
        pw.submit(Request(prompt(12), max_new_tokens=3))
        for _ in range(64):
            if not (pw.queue or pw.slots.active_slots or pw._chunk_streams):
                break
            pw.step()
        for _ in range(64):
            if done:
                break
            dw.step()
        assert done, "migration never completed — capture harness broken"

    migrate(None, "")
    migrate("int8", "_int8")

    gpt = GptEngine(*_gpt_toy(), max_slots=2, max_len=32, min_bucket=8,
                    donate_steps=True)
    recs["gpt_prefill"] = gpt._prefill = _Recorder(gpt._prefill)
    recs["gpt_decode"] = gpt._decode = _Recorder(gpt._decode)
    donated["gpt_prefill"] = (3, 4)
    donated["gpt_decode"] = (2, 3)
    gpt.serve([Request(prompt(10, 64), max_new_tokens=3),
               Request(prompt(6, 64), max_new_tokens=2)])

    out = {}
    for name, rec in recs.items():
        traced = rec.trace()
        if traced is None:
            continue  # program never dispatched (scheduler change?)
        out[name] = _from_traced(name, traced, rec.args,
                                 donated=donated[name], meta=meta)
    return out


@functools.lru_cache(maxsize=None)
def _gpt_toy():
    """Toy GPT-2 params/args for the router's second autoregressive
    family — same scale discipline as TOY (2 layers, degree-2-divisible
    heads, position table bounding max_len=32)."""
    from paddle_tpu.models.generation import (GPTGenArgs,
                                              gpt_params_from_layer)
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=64, hidden_size=32, intermediate_size=48,
                    num_hidden_layers=2, num_attention_heads=2,
                    max_position_embeddings=32)
    return gpt_params_from_layer(GPTForCausalLM(cfg)), \
        GPTGenArgs.from_config(cfg)
