"""Reusable jaxpr walker: one home for "visit every equation, including
the nested ones", so audit rules (and tests) stop hand-rolling partial
traversals.

Handles every place jax 0.4.x hides a subjaxpr:
  - pjit / closed_call / custom_jvp_call / custom_vjp_call_jaxpr carry a
    ClosedJaxpr under params["jaxpr"] / ["call_jaxpr"] / ["fun_jaxpr"];
  - scan / while carry ClosedJaxprs ("jaxpr", "cond_jaxpr", "body_jaxpr");
  - cond carries a TUPLE of ClosedJaxprs under "branches";
  - legacy shard_map carries an OPEN Jaxpr under "jaxpr".

The walker doesn't enumerate those keys — it scans every param value for
anything jaxpr-shaped (has `.eqns`, or wraps something that does), so new
primitives with new param names keep working.

Provenance: every equation carries `source_info`; `provenance(eqn)`
resolves it to the first non-jax user frame ("file.py:line (function)"),
which is what audit violations print so a finding names the line of
framework code that built the offending op.
"""

from __future__ import annotations

import os

__all__ = ["subjaxprs", "iter_eqns", "iter_shaped_values", "provenance",
           "user_frame", "format_eqn"]


def _as_open_jaxpr(item):
    """Jaxpr | ClosedJaxpr | anything -> open Jaxpr or None."""
    if hasattr(item, "eqns"):
        return item
    inner = getattr(item, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    return None


def subjaxprs(params):
    """Yield every open Jaxpr nested in an eqn's params dict (scalars,
    tuples and lists of jaxprs all handled; non-jaxpr values skipped)."""
    for v in params.values():
        items = v if isinstance(v, (tuple, list)) else (v,)
        for item in items:
            jx = _as_open_jaxpr(item)
            if jx is not None:
                yield jx


def iter_eqns(jaxpr):
    """DFS over (eqn, path) pairs of a Jaxpr/ClosedJaxpr and every nested
    subjaxpr. `path` is the tuple of enclosing primitive names, e.g.
    ("pjit", "shard_map", "scan") — the breadcrumb a violation message
    shows so "inside which program half" is never a guess. Cycles (shared
    subjaxpr objects) are visited once."""
    root = _as_open_jaxpr(jaxpr)
    if root is None:
        raise TypeError(f"not a jaxpr: {type(jaxpr).__name__}")
    seen = set()

    def walk(jx, path):
        if id(jx) in seen:
            return
        seen.add(id(jx))
        for eqn in jx.eqns:
            yield eqn, path
            sub_path = path + (eqn.primitive.name,)
            for sub in subjaxprs(eqn.params):
                yield from walk(sub, sub_path)

    yield from walk(root, ())


def iter_shaped_values(jaxpr):
    """Yield (aval, eqn, path, role) for every array-shaped value an
    equation reads ("in") or writes ("out"), across all subjaxprs.
    Literals are included (their avals carry shape/dtype too)."""
    for eqn, path in iter_eqns(jaxpr):
        for role, vs in (("in", eqn.invars), ("out", eqn.outvars)):
            for v in vs:
                aval = getattr(v, "aval", None)
                if aval is not None and hasattr(aval, "shape"):
                    yield aval, eqn, path, role


def user_frame(eqn):
    """Best-effort first user (non-jax-internal) frame of an equation's
    source_info. Returns an object with file_name / start_line /
    function_name, or None."""
    si = getattr(eqn, "source_info", None)
    if si is None:
        return None
    try:
        from jax._src import source_info_util as siu

        fr = siu.user_frame(si)
        if fr is not None:
            return fr
        # fall back to the raw traceback's innermost frame (user_frame
        # filters to non-jax code and can come up empty for ops built by
        # jax-internal helpers)
        tb = getattr(si, "traceback", None)
        frames = list(tb.frames) if tb is not None else []
        return frames[0] if frames else None
    except Exception:
        return None


def provenance(eqn):
    """Equation -> "file.py:line (function)" or "" when unavailable."""
    fr = user_frame(eqn)
    if fr is None:
        return ""
    fname = os.path.basename(getattr(fr, "file_name", "") or "")
    line = getattr(fr, "start_line", 0)
    func = getattr(fr, "function_name", "")
    return f"{fname}:{line} ({func})" if fname else ""


def format_eqn(eqn, path=()):
    """Short human label for an equation in a violation message."""
    shapes = ",".join(str(tuple(getattr(v.aval, "shape", ())))
                      for v in eqn.outvars if hasattr(v, "aval"))
    where = "/".join(path) if path else "top"
    return f"{eqn.primitive.name} -> {shapes} [{where}]"
