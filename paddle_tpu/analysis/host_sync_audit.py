"""Host-sync audit: step programs must not round-trip through the host.

A `pure_callback` / `debug_callback` / infeed inside a train or serving
step serializes the device against the Python thread every single step —
the kind of change that lands as "just a debug hook" and shows up weeks
later as a 30% device-idle mystery. The audit walks the program for
callback/transfer primitives and names the line that introduced one.

Rule id: host-sync.callback-in-step.
"""

from __future__ import annotations

from paddle_tpu.analysis.base import Violation
from paddle_tpu.analysis.jaxpr_walk import iter_eqns, provenance

__all__ = ["HOST_SYNC_PRIMITIVES", "check_host_sync"]

HOST_SYNC_PRIMITIVES = frozenset({
    "pure_callback",
    "io_callback",
    "debug_callback",
    "callback",
    "infeed",
    "outfeed",
    "host_local_array_to_global_array",
    "device_to_host", "host_to_device",
})


def check_host_sync(jaxpr, program, allowed=()):
    """Flag host-callback/transfer primitives anywhere in the program.
    `allowed` lists primitive names tolerated for this program (e.g. an
    input pipeline that genuinely infeeds)."""
    out = []
    for eqn, path in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name not in HOST_SYNC_PRIMITIVES or name in allowed:
            continue
        where = "/".join(path) if path else "top level"
        out.append(Violation(
            rule="host-sync.callback-in-step",
            program=program,
            message=(f"host round-trip primitive '{name}' inside the step "
                     f"program ({where}) — every step now blocks on the "
                     "Python thread"),
            provenance=provenance(eqn)))
    return out
