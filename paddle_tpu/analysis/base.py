"""Shared audit types: the Violation record every rule emits."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class Violation:
    """One broken invariant, with enough context to act on it.

    rule:       dotted rule id, e.g. "buffer.forbidden-shape".
    program:    the audited program's name (or the linted file).
    message:    what broke, in one sentence, with the offending numbers.
    provenance: best-effort "file.py:line (function)" of the offending
                equation (jaxpr source_info) or AST node.
    """

    rule: str
    program: str
    message: str
    provenance: str = ""

    def __str__(self):
        loc = f"  @ {self.provenance}" if self.provenance else ""
        return f"[{self.rule}] {self.program}: {self.message}{loc}"


def format_violations(violations):
    """Render a violation list as the block CI prints on failure."""
    if not violations:
        return "no violations"
    return "\n".join(str(v) for v in violations)
