"""Buffer audit: what the compiled program materializes.

Three rules over the jaxpr's intermediate values:

  top_intermediates    the k largest buffers any equation writes — the
                       report half (what would an HBM profile blame?).
  check_byte_ceiling   no single intermediate may exceed a per-program
                       byte budget (buffer.byte-ceiling). Budgets are
                       pinned per program family in analysis.presets.
  check_forbidden_shape  the generalized no-[b, s, vocab] rule from the
                       fused-CE work (buffer.forbidden-shape): the given
                       shape must not appear anywhere in the program,
                       forward or backward, including every subjaxpr.

`has_shape` is the predicate form (used by tests/test_fused_ce.py — the
traversal that used to live there as a private helper now has one home).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.analysis.base import Violation
from paddle_tpu.analysis.jaxpr_walk import (format_eqn, iter_eqns,
                                            iter_shaped_values, provenance)

__all__ = ["intermediates", "top_intermediates", "has_shape",
           "check_forbidden_shape", "check_byte_ceiling"]


def _nbytes(aval):
    try:
        return int(np.prod(aval.shape, dtype=np.int64)) * aval.dtype.itemsize
    except Exception:
        return 0


def intermediates(jaxpr):
    """Every buffer an equation writes: [(nbytes, aval, eqn, path)],
    deduped (an outvar read downstream is still one buffer), sorted
    largest-first."""
    out, seen = [], set()
    for eqn, path in iter_eqns(jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is None or not hasattr(aval, "shape") or id(v) in seen:
                continue
            seen.add(id(v))
            out.append((_nbytes(aval), aval, eqn, path))
    out.sort(key=lambda t: t[0], reverse=True)
    return out


def top_intermediates(jaxpr, k=10):
    """Top-k largest intermediates as report rows
    {nbytes, shape, dtype, op, provenance}."""
    return [{
        "nbytes": nb,
        "shape": tuple(aval.shape),
        "dtype": str(aval.dtype),
        "op": format_eqn(eqn, path),
        "provenance": provenance(eqn),
    } for nb, aval, eqn, path in intermediates(jaxpr)[:k]]


def has_shape(jaxpr, shape):
    """True iff any value (read or written, any subjaxpr) has exactly
    `shape`."""
    shape = tuple(shape)
    return any(tuple(aval.shape) == shape
               for aval, _, _, _ in iter_shaped_values(jaxpr))


def check_forbidden_shape(jaxpr, shape, program, what="buffer"):
    """No value of exactly `shape` may exist anywhere in the program.
    This is the standing form of the fused-CE no-[b, s, vocab] guarantee:
    pass shape=(b, s, vocab) and a rematerialized logits buffer — forward
    OR backward — fails the audit with the eqn that built it."""
    shape = tuple(shape)
    out = []
    seen_eqns = set()
    for aval, eqn, path, role in iter_shaped_values(jaxpr):
        if tuple(aval.shape) != shape or id(eqn) in seen_eqns:
            continue
        seen_eqns.add(id(eqn))
        out.append(Violation(
            rule="buffer.forbidden-shape",
            program=program,
            message=(f"forbidden {what} shape {shape} ({str(aval.dtype)}) "
                     f"{'read' if role == 'in' else 'written'} by "
                     f"{format_eqn(eqn, path)}"),
            provenance=provenance(eqn)))
        if len(out) >= 5:  # the first few sites identify the leak
            break
    return out


def check_byte_ceiling(jaxpr, ceiling_bytes, program):
    """No single intermediate may exceed `ceiling_bytes`. The budget is
    the audit's teeth against "a refactor quietly re-materialized the big
    buffer": presets pins one per program family at the landed program's
    high-water mark plus headroom."""
    out = []
    for nb, aval, eqn, path in intermediates(jaxpr):
        if nb <= ceiling_bytes:
            break  # sorted descending
        out.append(Violation(
            rule="buffer.byte-ceiling",
            program=program,
            message=(f"intermediate {tuple(aval.shape)} {str(aval.dtype)} "
                     f"is {nb} bytes > ceiling {ceiling_bytes} "
                     f"({format_eqn(eqn, path)})"),
            provenance=provenance(eqn)))
        if len(out) >= 5:
            break
    return out
