"""Dtype-policy audit: no stray f32 matmuls under a bf16 policy.

Under bf16 training every dot_general should take bf16 operands — an f32
dot runs the MXU at half rate and usually means a cast crept in upstream
(the classic silent 2x). The few *intentional* f32 sites (loss math, the
normalization stack, optimizer master-weight math) are allowlisted BY
PROVENANCE — file + function of the equation's source_info — so the
allowlist survives refactors that move lines but not functions.

Rule id: dtype.f32-dot-under-bf16.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.analysis.base import Violation
from paddle_tpu.analysis.jaxpr_walk import iter_eqns, provenance, user_frame

__all__ = ["DEFAULT_F32_DOT_ALLOWLIST", "check_dtype_policy"]

# "file.py::function" sites allowed to run f32 dot_generals under bf16:
# the loss epilogue accumulates in f32 by design, rms_norm's statistics
# are f32, and the optimizer's master-weight update is the entire point
# of keeping f32 around. Everything else must justify itself here.
DEFAULT_F32_DOT_ALLOWLIST = (
    "llama_functional.py::parallel_cross_entropy",
    "llama_functional.py::_ce_chunk_stats",
    "llama_functional.py::_fused_ce_fwd",
    "llama_functional.py::_fused_ce_bwd",
    "llama_functional.py::rms_norm",
    "llama_functional.py::apply_rope_bcast",
    "llama_functional.py::apply_rope",
    "hybrid_engine.py::upd",          # adamw master-weight math
    "hybrid_engine.py::adamw_update",
)


def _allowed(eqn, allowlist):
    fr = user_frame(eqn)
    if fr is None:
        return False
    fname = str(getattr(fr, "file_name", "") or "")
    func = str(getattr(fr, "function_name", "") or "")
    for entry in allowlist:
        efile, _, efunc = entry.partition("::")
        if fname.endswith(efile) and (not efunc or efunc == func):
            return True
    return False


def check_dtype_policy(jaxpr, program, policy="bf16",
                       allowlist=DEFAULT_F32_DOT_ALLOWLIST):
    """Flag f32-operand dot_generals when the program's compute policy is
    bf16. `policy` other than "bf16" disables the rule (f32 training is
    allowed to be f32)."""
    if policy != "bf16":
        return []
    out = []
    for eqn, path in iter_eqns(jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        op_dtypes = [getattr(getattr(v, "aval", None), "dtype", None)
                     for v in eqn.invars]
        if not any(d is not None and np.dtype(d) == np.dtype(np.float32)
                   for d in op_dtypes):
            continue
        if _allowed(eqn, allowlist):
            continue
        shapes = [tuple(getattr(getattr(v, "aval", None), "shape", ()))
                  for v in eqn.invars]
        out.append(Violation(
            rule="dtype.f32-dot-under-bf16",
            program=program,
            message=(f"f32 dot_general {shapes[0]} x {shapes[1]} under "
                     "bf16 policy (half MXU rate); cast operands to bf16 "
                     "or allowlist the site with a justification"),
            provenance=provenance(eqn)))
    return out
