"""Collective audit: census + fingerprint of a program's collectives.

The TP serving programs (and the hybrid engine's shard_map step) have a
*known* collective structure: every row-parallel matmul carries exactly
one psum reduce epilogue — 2 per decoder layer (wo, w_down), nothing
else. An accidentally doubled psum (e.g. a helper that reduces AND a
caller that reduces again) is numerically WRONG only for non-idempotent
content but always slow; a dropped psum is silently wrong on >1 chips and
invisible on the dp=1 CI rig. End-to-end parity catches these late and
expensively — the census catches them at trace time.

  collective_census(jaxpr)   ordered [(prim, axes, shape)] of every
                             collective, in program order.
  fingerprint(census)        stable 12-hex digest of the (prim, axes)
                             sequence — goldens pin it per program.
  check_collectives(...)     count and/or fingerprint must match.

Rule ids: collective.count-mismatch, collective.fingerprint-mismatch.
"""

from __future__ import annotations

import hashlib

from paddle_tpu.analysis.base import Violation
from paddle_tpu.analysis.jaxpr_walk import iter_eqns, provenance

__all__ = ["COLLECTIVE_PRIMITIVES", "collective_census", "fingerprint",
           "check_collectives"]

COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "psum2", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "psum_scatter", "reduce_scatter",
})


def _axes_of(eqn):
    params = eqn.params
    axes = params.get("axes", params.get("axis_name", ()))
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def collective_census(jaxpr, prims=COLLECTIVE_PRIMITIVES):
    """Ordered census of the program's collectives:
    [{prim, axes, shape, provenance}] in deterministic walk order."""
    out = []
    for eqn, path in iter_eqns(jaxpr):
        if eqn.primitive.name not in prims:
            continue
        shape = ()
        if eqn.outvars and hasattr(eqn.outvars[0], "aval"):
            shape = tuple(getattr(eqn.outvars[0].aval, "shape", ()))
        out.append({
            "prim": eqn.primitive.name,
            "axes": _axes_of(eqn),
            "shape": shape,
            "path": "/".join(path),
            "provenance": provenance(eqn),
        })
    return out


def fingerprint(census):
    """Order-sensitive digest of the (prim, axes) sequence. Shapes are
    excluded so the fingerprint is stable across batch-size/toy-size
    changes; a doubled, dropped, or reordered collective changes it."""
    text = ";".join(f"{c['prim']}@{','.join(c['axes'])}" for c in census)
    return hashlib.sha1(text.encode()).hexdigest()[:12]


def check_collectives(jaxpr, program, expect_count=None,
                      expect_fingerprint=None, prims=COLLECTIVE_PRIMITIVES):
    """Pin the program's collective structure. `expect_count` is usually
    a formula of the model (2 * num_layers psums for Megatron TP);
    `expect_fingerprint` is the golden digest — regenerate with
    `fingerprint(collective_census(jaxpr))` after an INTENTIONAL change
    and say why in the diff."""
    census = collective_census(jaxpr, prims=prims)
    out = []
    if expect_count is not None and len(census) != expect_count:
        sites = ", ".join(
            f"{c['prim']}@{','.join(c['axes'])} [{c['provenance']}]"
            for c in census[:6]) or "none"
        out.append(Violation(
            rule="collective.count-mismatch",
            program=program,
            message=(f"expected {expect_count} collectives, found "
                     f"{len(census)}: {sites}"
                     + (" ..." if len(census) > 6 else "")),
            provenance=census[0]["provenance"] if census else ""))
    if expect_fingerprint is not None:
        got = fingerprint(census)
        if got != expect_fingerprint:
            seq = ";".join(f"{c['prim']}@{','.join(c['axes'])}"
                           for c in census)
            out.append(Violation(
                rule="collective.fingerprint-mismatch",
                program=program,
                message=(f"collective fingerprint {got} != golden "
                         f"{expect_fingerprint} (sequence: {seq or 'empty'})"
                         " — doubled/dropped/reordered collective, or an "
                         "intentional change that must update the golden"),
                provenance=census[0]["provenance"] if census else ""))
    return out
