"""The wired-up CPU audit: every rule over every real program family.

`run_cpu_audits()` is the single entry point tier-1 and tools/lint.py
share. It builds the five program families at toy size (fused-CE
fwd+bwd, the hybrid engine's train step, the fused optimizer
write-back, the PagedEngine's captured serving steps, and the
disaggregated-serving migration + router-GPT programs) and applies the
rule suite with the repo's pinned invariants:

  - no [batch, seq, vocab] intermediate anywhere near the loss;
  - per-program byte ceilings on the largest intermediate (backstop for
    shape regressions the forbidden-shape probe doesn't name);
  - donated state (params, opt state, KV page pool) actually aliased in
    the lowered/compiled program;
  - bf16 AMP: f32 dot_generals only at allowlisted loss/norm sites;
  - no host callbacks in any step program;
  - TP serving collectives pinned by count AND fingerprint — every
    row-parallel matmul carries exactly one psum reduce epilogue (the
    decoder layers are scanned, so the static census is per-body: 2
    psums over ('mp',), for any layer count).

GOLDEN fingerprints are regenerated with
`collective_audit.fingerprint(collective_audit.collective_census(j))`
after an INTENTIONAL collective change — say why in the diff.
"""

from __future__ import annotations

from paddle_tpu.analysis import (buffer_audit, collective_audit,
                                 donation_audit, dtype_audit,
                                 host_sync_audit, programs)

__all__ = ["GOLDEN_COLLECTIVES", "GOLDEN_DISAGG", "BYTE_CEILINGS",
           "run_cpu_audits"]

# static collective structure of each serving program: the layer stack
# is a scan, so the census counts the body once — 2 row-parallel psum
# epilogues (wo, w_down) regardless of num_layers; page_copy is pure
# data movement and must stay collective-free
_TP_FP = "a91763b43edf"       # psum@mp;psum@mp
_EMPTY_FP = "da39a3ee5e6b"    # empty census
GOLDEN_COLLECTIVES = {
    "paged_prefill": (2, _TP_FP),
    "paged_decode": (2, _TP_FP),
    "spec_verify": (2, _TP_FP),
    "page_copy": (0, _EMPTY_FP),
    # kv_dtype='int8' family: quantize-at-scatter / dequant-at-gather are
    # elementwise per shard, so the census must be IDENTICAL to the
    # model-dtype pool — and the int8 page copy (codes + scale leaves)
    # stays collective-free
    "paged_prefill_int8": (2, _TP_FP),
    "paged_decode_int8": (2, _TP_FP),
    "page_copy_int8": (0, _EMPTY_FP),
}

# the disaggregated-serving + router family is its OWN golden dict: the
# serving captures above must not silently grow entries when disagg
# programs change (and vice versa). The migration pair is pure data
# movement — a collective creeping into extract/scatter would put a
# cross-shard hop on every hand-off; the GPT stripe programs are
# single-chip (the router's second model family has no TP mesh).
GOLDEN_DISAGG = {
    "page_extract": (0, _EMPTY_FP),
    "page_scatter": (0, _EMPTY_FP),
    "page_extract_int8": (0, _EMPTY_FP),
    "page_scatter_int8": (0, _EMPTY_FP),
    "gpt_prefill": (0, _EMPTY_FP),
    "gpt_decode": (0, _EMPTY_FP),
}

# largest-intermediate ceilings at the toy geometry (measured max plus
# ~40% headroom): a blowup past these means a buffer class that did not
# exist when the budget was pinned
BYTE_CEILINGS = {
    "fused_ce_fwd_bwd": 12 * 1024,
    "hybrid_train_step": 18 * 1024,
    "fused_opt_writeback": 18 * 1024,
    "paged_prefill": 26 * 1024,
    "paged_decode": 26 * 1024,
    "spec_verify": 26 * 1024,
    "page_copy": 26 * 1024,
    # int8 pool: the pool buffers shrink 2-4x but the prefill gather
    # dequantizes pages to f32 before attention, so the ceilings stay at
    # the model-dtype budget rather than scaling with the pool
    "paged_prefill_int8": 26 * 1024,
    "paged_decode_int8": 26 * 1024,
    "page_copy_int8": 26 * 1024,
    # disagg migration: extract gathers ONE request's pages (measured 4K
    # model-dtype / 1K int8 codes at toy size); scatter's largest buffer
    # is the destination pool leaf it writes through (18K / 4.5K). The
    # GPT stripe programs top out at the [slots, heads, len, hd] KV
    # stripe (16K).
    "page_extract": 6 * 1024,
    "page_extract_int8": 2 * 1024,
    "page_scatter": 26 * 1024,
    "page_scatter_int8": 7 * 1024,
    "gpt_prefill": 23 * 1024,
    "gpt_decode": 23 * 1024,
}

_TRAIN_ARG_NAMES = ("params", "opt_state", "ids", "labels")
_OPT_ARG_NAMES = ("params", "grads", "opt_state")


def _common(p, out):
    """Rules every program family gets: host-sync ban + byte ceiling."""
    out += host_sync_audit.check_host_sync(p.jaxpr, p.name)
    ceiling = BYTE_CEILINGS.get(p.name)
    if ceiling is not None:
        out += buffer_audit.check_byte_ceiling(p.jaxpr, ceiling, p.name)


def _donation(p, out, arg_names=None):
    out += donation_audit.check_donation(
        p.lowered_text, p.example_args, p.donated, p.name,
        arg_names=arg_names, kept=p.kept, compiled_text=p.compiled_text)


def audit_fused_ce():
    fused, _ = programs.fused_ce_programs()
    out = []
    out += buffer_audit.check_forbidden_shape(
        fused.jaxpr, fused.meta["forbidden_shape"], fused.name,
        "full-logits")
    _common(fused, out)
    return out


def audit_train_step():
    p = programs.train_step_program()
    out = []
    out += buffer_audit.check_forbidden_shape(
        p.jaxpr, p.meta["forbidden_shape"], p.name, "full-logits")
    out += dtype_audit.check_dtype_policy(p.jaxpr, p.name,
                                          policy=p.meta["policy"])
    _donation(p, out, _TRAIN_ARG_NAMES)
    _common(p, out)
    return out


def audit_opt_writeback():
    p = programs.opt_writeback_program()
    out = []
    _donation(p, out, _OPT_ARG_NAMES)
    _common(p, out)
    return out


def audit_serving(tp=2):
    progs = programs.serving_programs(tp=tp)
    out = []
    from paddle_tpu.analysis.base import Violation
    missing = sorted(set(GOLDEN_COLLECTIVES) - set(progs))
    for name in missing:
        # a family that silently stopped being captured is itself a
        # finding — the audit must not go blind without failing
        out.append(Violation(
            rule="audit.program-not-captured", program=name,
            message="serving program was never dispatched/captured — "
                    "scheduler or capture-harness change?"))
    for name, p in sorted(progs.items()):
        count, fp = GOLDEN_COLLECTIVES.get(name, (None, None))
        out += collective_audit.check_collectives(
            p.jaxpr, name, expect_count=count, expect_fingerprint=fp)
        _donation(p, out)
        _common(p, out)
    return out


def audit_disagg():
    """The disaggregated-serving family: KV-page migration programs
    (model-dtype + int8 pools) and the router's GPT stripe programs —
    census pinned by GOLDEN_DISAGG, scatter/stripe donation aliased,
    host-sync ban + byte ceilings throughout."""
    progs = programs.disagg_programs()
    out = []
    from paddle_tpu.analysis.base import Violation
    for name in sorted(set(GOLDEN_DISAGG) - set(progs)):
        out.append(Violation(
            rule="audit.program-not-captured", program=name,
            message="disagg program was never dispatched/captured — "
                    "scheduler or capture-harness change?"))
    for name, p in sorted(progs.items()):
        count, fp = GOLDEN_DISAGG.get(name, (None, None))
        out += collective_audit.check_collectives(
            p.jaxpr, name, expect_count=count, expect_fingerprint=fp)
        _donation(p, out)
        _common(p, out)
    return out


def run_cpu_audits(families=("fused_ce", "train_step", "opt_writeback",
                             "serving", "disagg")):
    """Run every audit family; returns the full list of Violations
    (empty = the repo's compiled programs uphold every invariant)."""
    runners = {
        "fused_ce": audit_fused_ce,
        "train_step": audit_train_step,
        "opt_writeback": audit_opt_writeback,
        "serving": audit_serving,
        "disagg": audit_disagg,
    }
    out = []
    for fam in families:
        out += runners[fam]()
    return out
