"""Donation audit: donated inputs must actually alias an output.

`donate_argnums` is a *request* — jax silently drops the alias when
shapes/dtypes don't line up with any output (the "Some donated buffers
were not usable" warning is easy to lose in CI logs), and a refactor
that, say, casts the opt state on the way out doubles the optimizer's
HBM without failing anything. This audit reads the lowered MLIR, where a
kept alias is explicit: donated-and-used arguments carry
`tf.aliasing_output = N` on the @main signature.

Audited invariants (wired up in analysis.presets):
  - the train step's params AND opt state are fully aliased (no
    double-buffered master weights / moments);
  - the fused optimizer write-back aliases params + opt state;
  - the serving step programs alias the KV page pool (pk/pv) — the
    buffers the engine threads through every step.

Two lowering flavours carry the evidence differently:
  - single-device jit writes the KEPT alias directly on the StableHLO
    @main signature: `tf.aliasing_output = N`;
  - SPMD (mesh/shard_map) lowering only marks the request
    (`jax.buffer_donor = true`) and resolves aliasing at compile time —
    there the proof lives in the compiled HLO header:
    `input_output_alias={ {out}: (param, {}, may-alias), ... }`.
`check_donation` accepts both: pass `compiled_text` for mesh programs.

The flat argument index in MLIR is the flattened (args, kwargs) leaf
order, which is how `check_donation` maps "argument 6's pytree" onto
`%argN` attributes. Caveat: jit prunes UNUSED args from the lowering
(keep_unused=False default), which shifts indices — pass `kept` (the
lowering's kept_var_idx) to remap, or `check_donation` cross-checks the
lowered arg count against the flattened count and refuses to guess when
they disagree.
"""

from __future__ import annotations

import re

import jax

from paddle_tpu.analysis.base import Violation

__all__ = ["alias_map", "hlo_alias_map", "arg_offsets", "check_donation"]

_ARG_RE = re.compile(r"%arg(\d+):")
_ALIAS_ATTR_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")
# compiled-HLO header entry: "{0}: (3, {}, may-alias)" — output tuple
# path, then the parameter index
_HLO_ALIAS_ENTRY_RE = re.compile(r"\{([\d,\s]*)\}:\s*\((\d+)")


def _main_signature(mlir_text):
    """The @main func signature line (aliasing attrs live only there)."""
    for line in mlir_text.splitlines():
        if "func.func public @main" in line:
            return line
    return mlir_text  # fall back to scanning everything


def alias_map(lowered_or_text):
    """Lowered (or its MLIR text) -> {flat_arg_index: output_index} of the
    aliases the lowering actually kept. Parsed per-argument segment (the
    attr dict can nest braces, e.g. mhlo.sharding = "{replicated}", so a
    single regex across the signature would misparse):
    "%arg7: tensor<2x64xf32> {..., tf.aliasing_output = 3 : i32}"."""
    text = (lowered_or_text if isinstance(lowered_or_text, str)
            else lowered_or_text.as_text())
    sig = _main_signature(text)
    hits = list(_ARG_RE.finditer(sig))
    out = {}
    for i, m in enumerate(hits):
        seg = sig[m.end():hits[i + 1].start() if i + 1 < len(hits)
                  else len(sig)]
        alias = _ALIAS_ATTR_RE.search(seg)
        if alias:
            out[int(m.group(1))] = int(alias.group(1))
    return out


def hlo_alias_map(compiled_text):
    """Compiled-HLO text -> {param_index: output_tuple_path} from the
    module header's input_output_alias directive (the SPMD path: mesh
    lowerings resolve donation at compile time, not in StableHLO). The
    block nests braces ({0}: (3, {}, may-alias)) so it is brace-counted,
    not regexed, out of the header."""
    key = "input_output_alias={"
    i = compiled_text.find(key)
    if i < 0:
        return {}
    j, depth = i + len(key), 1
    while j < len(compiled_text) and depth:
        c = compiled_text[j]
        depth += (c == "{") - (c == "}")
        j += 1
    block = compiled_text[i + len(key):j - 1]
    return {int(m.group(2)): m.group(1).strip()
            for m in _HLO_ALIAS_ENTRY_RE.finditer(block)}


def _main_arg_count(mlir_text):
    sig = _main_signature(mlir_text)
    idxs = [int(m) for m in _ARG_RE.findall(sig)]
    return (max(idxs) + 1) if idxs else 0


def arg_offsets(example_args):
    """Positional example args -> [(start, n_leaves)] so argnum i's leaves
    occupy flat MLIR args [start, start + n)."""
    offsets, pos = [], 0
    for a in example_args:
        n = len(jax.tree_util.tree_leaves(a))
        offsets.append((pos, n))
        pos += n
    return offsets


def check_donation(lowered, example_args, donated_argnums, program,
                   arg_names=None, kept=None, compiled_text=None):
    """Every leaf of every donated positional arg must carry a kept alias
    in the lowered program. `example_args` must be the same positional
    structure the program was lowered with (ShapeDtypeStructs are fine —
    only the tree structure is read). `kept` is the lowering's
    kept_var_idx (original flat indices that survived unused-arg
    pruning); pruned donated leaves hold no buffer and are skipped.
    `compiled_text` supplies the compiled-HLO input_output_alias header
    for SPMD programs, whose StableHLO only records the donation request
    (jax.buffer_donor), not the resolved alias."""
    text = lowered if isinstance(lowered, str) else lowered.as_text()
    aliases = dict(alias_map(text))
    if compiled_text:
        aliases.update(hlo_alias_map(compiled_text))
    offsets = arg_offsets(example_args)
    total = sum(n for _, n in offsets)
    lowered_n = _main_arg_count(text)
    out = []
    if kept is not None:
        # MLIR arg j is the j-th kept original index
        rank = {orig: j for j, orig in enumerate(sorted(kept))}
        expect_n = len(kept)
    else:
        rank = {i: i for i in range(total)}
        expect_n = total
    if lowered_n != expect_n:
        # misaligned indices would garble every report below — report the
        # mismatch itself instead of guessing
        return [Violation(
            rule="donation.arg-mismatch",
            program=program,
            message=(f"lowered @main has {lowered_n} args but expected "
                     f"{expect_n} ({total} example leaves"
                     + (f", {len(kept)} kept" if kept is not None else "")
                     + ") — donation audit cannot map argnums"))]
    for argnum in donated_argnums:
        start, n = offsets[argnum]
        name = (arg_names[argnum] if arg_names else f"arg{argnum}")
        leaves, _ = jax.tree_util.tree_flatten_with_path(
            example_args[argnum])
        missing = [
            (i, leaves[i][0] if i < len(leaves) else None)
            for i in range(n)
            if (start + i) in rank and rank[start + i] not in aliases]
        for i, path in missing[:5]:
            leaf = jax.tree_util.keystr(path) if path is not None else f"[{i}]"
            out.append(Violation(
                rule="donation.not-aliased",
                program=program,
                message=(f"donated input {name}{leaf} (flat arg "
                         f"{start + i}) has no input-output alias in the "
                         "lowered program — its HBM is double-buffered"),
            ))
        if len(missing) > 5:
            out.append(Violation(
                rule="donation.not-aliased", program=program,
                message=(f"... and {len(missing) - 5} more unaliased "
                         f"leaves of {name}")))
    return out
