"""paddle.utils (reference `python/paddle/utils/__init__.py`):
deprecation decorator, version gate, install self-check, soft import."""

from __future__ import annotations

import functools
import importlib
import warnings

__all__ = ["deprecated", "require_version", "run_check", "try_import"]


def deprecated(update_to="", since="", reason="", level=0):
    """reference utils/deprecated.py: warn (level<=1) or raise (level==2)
    on use of a deprecated API."""

    def decorator(fn):
        msg = f"API \"{fn.__module__}.{fn.__name__}\" is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use \"{update_to}\" instead"
        if reason:
            msg += f". Reason: {reason}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level == 2:
                raise RuntimeError(msg)
            if level < 2:
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return decorator


def require_version(min_version, max_version=None):
    """reference utils/layers_utils.py require_version: raise unless the
    installed version is within [min_version, max_version]."""
    import paddle_tpu

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())

    cur = parse(paddle_tpu.__version__)
    if min_version and cur < parse(min_version):
        raise RuntimeError(
            f"paddle version {paddle_tpu.__version__} < required "
            f"{min_version}")
    if max_version and cur > parse(max_version):
        raise RuntimeError(
            f"paddle version {paddle_tpu.__version__} > allowed "
            f"{max_version}")
    return True


def run_check():
    """reference utils/install_check.py run_check: a tiny end-to-end
    train step on the current device, printing the verdict."""
    import paddle_tpu as paddle
    from paddle_tpu import nn

    dev = paddle.get_device()
    m = nn.Linear(4, 2)
    x = paddle.randn([8, 4])
    loss = (m(x) ** 2).mean()
    loss.backward()
    assert m.weight.grad is not None
    print(f"PaddlePaddle (tpu-native) works fine on {dev}.")
    print("PaddlePaddle (tpu-native) is installed successfully!")


def try_import(module_name, err_msg=None):
    """reference utils/lazy_import.py try_import: import or raise with an
    install hint."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"Failed to import {module_name}. This environment "
            "is hermetic (no pip install); the dependency must be baked "
            "into the image.") from e
