"""paddle.text: sequence decoding utilities (reference:
`python/paddle/text/viterbi_decode.py`; kernel
`paddle/phi/kernels/viterbi_decode_kernel.*`).

TPU-native: the Viterbi DP is a `lax.scan` over time steps (static control
flow) followed by a reverse scan for the backtrace — no host round trips.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["viterbi_decode", "ViterbiDecoder", "Imdb",
           "Imikolov", "Movielens", "UCIHousing",
           "Conll05st", "WMT14", "WMT16"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """Best tag sequence under a linear-chain CRF.

    potentials: [B, T, N] emissions; transition_params: [N, N];
    lengths: [B] (defaults to full length). Returns (scores [B],
    paths [B, T]).
    """
    em = potentials._data if isinstance(potentials, Tensor) else potentials
    tr = (transition_params._data
          if isinstance(transition_params, Tensor) else transition_params)
    b, t, n = em.shape
    lens = (lengths._data if isinstance(lengths, Tensor)
            else jnp.full((b,), t, jnp.int32) if lengths is None
            else jnp.asarray(lengths))

    def step(carry, xs):
        alpha, ti = carry
        emit = xs  # [B, N]
        # score of arriving at tag j from best i
        scores = alpha[:, :, None] + tr[None]  # [B, N(from), N(to)]
        best_prev = jnp.argmax(scores, axis=1)  # [B, N]
        new_alpha = jnp.max(scores, axis=1) + emit
        # positions past a sequence's length keep their alpha frozen
        active = (ti < lens)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        best_prev = jnp.where(active, best_prev,
                              jnp.arange(n)[None, :])
        return (new_alpha, ti + 1), best_prev

    alpha0 = em[:, 0]
    (alpha, _), backptrs = jax.lax.scan(
        step, (alpha0, jnp.ones((b,), jnp.int32)),
        jnp.moveaxis(em[:, 1:], 1, 0))
    scores = jnp.max(alpha, axis=-1)
    last = jnp.argmax(alpha, axis=-1)  # [B]

    def back(carry, bp):
        tag = carry
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    _, path_rev = jax.lax.scan(back, last, backptrs, reverse=True)
    paths = jnp.concatenate([jnp.moveaxis(path_rev, 0, 1), last[:, None]],
                            axis=1)
    return Tensor(scores), Tensor(paths.astype(jnp.int64))


class ViterbiDecoder:
    """Reference `text/viterbi_decode.py` ViterbiDecoder layer-style API."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# -- paddle.text.datasets (reference `python/paddle/text/datasets/*`):
# -- with no network egress, local corpora load when given; otherwise
# -- deterministic synthetic samples keep training loops runnable (same
# -- convention as paddle_tpu.vision.datasets) ------------------------------

from paddle_tpu.io import Dataset as _Dataset  # noqa: E402


class _SyntheticTextDataset(_Dataset):
    _N_TRAIN = 1024
    _N_TEST = 256

    def __init__(self, data_file=None, mode="train", **kw):
        import numpy as np

        self.mode = mode
        n = self._N_TRAIN if mode in ("train", "training") else self._N_TEST
        rng = np.random.RandomState(0 if mode in ("train", "training")
                                    else 1)
        self._items = self._synthesize(rng, n)

    def __getitem__(self, idx):
        return self._items[idx]

    def __len__(self):
        return len(self._items)


class Imdb(_SyntheticTextDataset):
    """reference `text/datasets/imdb.py`: (token_ids, 0/1 sentiment)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, **kw):
        self._cutoff = cutoff
        super().__init__(data_file, mode)

    def _synthesize(self, rng, n):
        import numpy as np

        items = []
        for _ in range(n):
            label = rng.randint(0, 2)
            L = rng.randint(8, 64)
            # class-coded token distribution so models can actually learn
            base = 10 if label else 200
            toks = (base + rng.randint(0, 50, L)).astype(np.int64)
            items.append((toks, np.int64(label)))
        return items


class Imikolov(_SyntheticTextDataset):
    """reference `text/datasets/imikolov.py`: n-gram LM tuples."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, **kw):
        self._win = window_size
        super().__init__(data_file, mode)

    def _synthesize(self, rng, n):
        import numpy as np

        return [tuple(rng.randint(0, 2000, self._win).astype(np.int64))
                for _ in range(n)]


class Movielens(_SyntheticTextDataset):
    """reference `text/datasets/movielens.py`: (user, gender, age, job,
    movie, categories, title, rating)."""

    def _synthesize(self, rng, n):
        import numpy as np

        items = []
        for _ in range(n):
            items.append((np.int64(rng.randint(1, 6041)),
                          np.int64(rng.randint(0, 2)),
                          np.int64(rng.randint(0, 7)),
                          np.int64(rng.randint(0, 21)),
                          np.int64(rng.randint(1, 3953)),
                          rng.randint(0, 18, 3).astype(np.int64),
                          rng.randint(0, 5000, 4).astype(np.int64),
                          np.float32(rng.randint(1, 6))))
        return items


class UCIHousing(_SyntheticTextDataset):
    """reference `text/datasets/uci_housing.py`: (13 features, price)."""

    def _synthesize(self, rng, n):
        import numpy as np

        w = rng.randn(13).astype(np.float32)
        items = []
        for _ in range(n):
            x = rng.randn(13).astype(np.float32)
            y = np.float32(x @ w + 0.1 * rng.randn())
            items.append((x, np.asarray([y], np.float32)))
        return items


class Conll05st(_SyntheticTextDataset):
    """reference `text/datasets/conll05.py`: SRL tuples (word, ctx...,
    mark, label sequences)."""

    def _synthesize(self, rng, n):
        import numpy as np

        items = []
        for _ in range(n):
            L = rng.randint(5, 30)
            seqs = [rng.randint(0, 5000, L).astype(np.int64)
                    for _ in range(7)]
            mark = rng.randint(0, 2, L).astype(np.int64)
            label = rng.randint(0, 67, L).astype(np.int64)
            items.append((*seqs, mark, label))
        return items


class _WMT(_SyntheticTextDataset):
    _SRC_V = 3000
    _TGT_V = 3000

    def __init__(self, data_file=None, mode="train", dict_size=-1, **kw):
        super().__init__(data_file, mode)

    def _synthesize(self, rng, n):
        import numpy as np

        items = []
        for _ in range(n):
            ls = rng.randint(4, 24)
            lt = rng.randint(4, 24)
            src = rng.randint(3, self._SRC_V, ls).astype(np.int64)
            # teacher-forcing form: (src, trg, trg_next)
            trg = rng.randint(3, self._TGT_V, lt).astype(np.int64)
            items.append((src, trg, np.roll(trg, -1)))
        return items


class WMT14(_WMT):
    """reference `text/datasets/wmt14.py`."""


class WMT16(_WMT):
    """reference `text/datasets/wmt16.py`."""
