"""paddle.text: sequence decoding utilities (reference:
`python/paddle/text/viterbi_decode.py`; kernel
`paddle/phi/kernels/viterbi_decode_kernel.*`).

TPU-native: the Viterbi DP is a `lax.scan` over time steps (static control
flow) followed by a reverse scan for the backtrace — no host round trips.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """Best tag sequence under a linear-chain CRF.

    potentials: [B, T, N] emissions; transition_params: [N, N];
    lengths: [B] (defaults to full length). Returns (scores [B],
    paths [B, T]).
    """
    em = potentials._data if isinstance(potentials, Tensor) else potentials
    tr = (transition_params._data
          if isinstance(transition_params, Tensor) else transition_params)
    b, t, n = em.shape
    lens = (lengths._data if isinstance(lengths, Tensor)
            else jnp.full((b,), t, jnp.int32) if lengths is None
            else jnp.asarray(lengths))

    def step(carry, xs):
        alpha, ti = carry
        emit = xs  # [B, N]
        # score of arriving at tag j from best i
        scores = alpha[:, :, None] + tr[None]  # [B, N(from), N(to)]
        best_prev = jnp.argmax(scores, axis=1)  # [B, N]
        new_alpha = jnp.max(scores, axis=1) + emit
        # positions past a sequence's length keep their alpha frozen
        active = (ti < lens)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        best_prev = jnp.where(active, best_prev,
                              jnp.arange(n)[None, :])
        return (new_alpha, ti + 1), best_prev

    alpha0 = em[:, 0]
    (alpha, _), backptrs = jax.lax.scan(
        step, (alpha0, jnp.ones((b,), jnp.int32)),
        jnp.moveaxis(em[:, 1:], 1, 0))
    scores = jnp.max(alpha, axis=-1)
    last = jnp.argmax(alpha, axis=-1)  # [B]

    def back(carry, bp):
        tag = carry
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    _, path_rev = jax.lax.scan(back, last, backptrs, reverse=True)
    paths = jnp.concatenate([jnp.moveaxis(path_rev, 0, 1), last[:, None]],
                            axis=1)
    return Tensor(scores), Tensor(paths.astype(jnp.int64))


class ViterbiDecoder:
    """Reference `text/viterbi_decode.py` ViterbiDecoder layer-style API."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
