"""paddle.audio.datasets (reference `python/paddle/audio/datasets/
{esc50,tess}.py`): ESC-50 and TESS. Zero-egress image — the real archives
cannot be downloaded, so these are deterministic synthetic stand-ins with
the reference's exact shapes/label spaces (same pattern as
paddle_tpu.text datasets), suitable for pipeline and feature tests."""

from __future__ import annotations

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["ESC50", "TESS"]


class _SyntheticAudioDataset(Dataset):
    N_CLASSES = 2
    SAMPLE_RATE = 16000
    DURATION = 1.0  # seconds per clip (reference clips are longer; kept
    # short so feature extraction in tests stays fast)

    def __init__(self, mode="train", feat_type="raw", seed=0, n_items=64,
                 **feat_kwargs):
        self.mode = mode
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs
        rng = np.random.default_rng(seed + (0 if mode == "train" else 1))
        n = n_items if mode == "train" else max(8, n_items // 4)
        t = int(self.SAMPLE_RATE * self.DURATION)
        self.labels = rng.integers(0, self.N_CLASSES, n).astype("int64")
        # label-dependent tone + noise so classifiers can actually learn
        base = np.linspace(0, self.DURATION, t, dtype="float32")
        self.waves = np.stack([
            np.sin(2 * np.pi * (200 + 50 * int(lb)) * base)
            + 0.1 * rng.standard_normal(t).astype("float32")
            for lb in self.labels
        ]).astype("float32")

    def _feature(self, wav):
        if self.feat_type == "raw":
            return wav
        from paddle_tpu.audio import features
        import paddle_tpu as paddle

        x = paddle.to_tensor(wav[None, :])
        if self.feat_type == "mfcc":
            return features.MFCC(sr=self.SAMPLE_RATE,
                                 **self.feat_kwargs)(x).numpy()[0]
        if self.feat_type == "logmelspectrogram":
            return features.LogMelSpectrogram(
                sr=self.SAMPLE_RATE, **self.feat_kwargs)(x).numpy()[0]
        if self.feat_type == "melspectrogram":
            return features.MelSpectrogram(
                sr=self.SAMPLE_RATE, **self.feat_kwargs)(x).numpy()[0]
        if self.feat_type == "spectrogram":
            return features.Spectrogram(**self.feat_kwargs)(x).numpy()[0]
        raise ValueError(f"unknown feat_type {self.feat_type!r}")

    def __getitem__(self, idx):
        return self._feature(self.waves[idx]), self.labels[idx]

    def __len__(self):
        return len(self.labels)


class ESC50(_SyntheticAudioDataset):
    """reference audio/datasets/esc50.py: 50 environmental sound classes."""

    N_CLASSES = 50
    SAMPLE_RATE = 44100
    DURATION = 0.25

    def __init__(self, mode="train", split=1, feat_type="raw", **kw):
        super().__init__(mode=mode, feat_type=feat_type, seed=split, **kw)


class TESS(_SyntheticAudioDataset):
    """reference audio/datasets/tess.py: 7 emotion classes."""

    N_CLASSES = 7
    SAMPLE_RATE = 24414
    DURATION = 0.25

    def __init__(self, mode="train", n_folds=1, split=1, feat_type="raw",
                 **kw):
        super().__init__(mode=mode, feat_type=feat_type, seed=split, **kw)
