"""paddle.audio (reference: `python/paddle/audio/` — mel/fbank functional
utilities + Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC feature
layers over the signal stack).

TPU-native: everything is jnp over the framework's `signal.stft` /
`fft` modules, so feature extraction jits and fuses into the model's first
conv. Backends (soundfile IO) are host-side; the zero-egress environment
ships no codecs, so `load` accepts wav via the stdlib `wave` module only.
"""

from paddle_tpu.audio import backends  # noqa: F401
from paddle_tpu.audio import functional  # noqa: F401
from paddle_tpu.audio import features  # noqa: F401
from paddle_tpu.audio.backends import load, save, info  # noqa: F401

from paddle_tpu.audio import datasets  # noqa: F401,E402

__all__ = ["functional", "features", "backends", "datasets", "load", "save", "info"]
