"""Audio IO backends (reference: `python/paddle/audio/backends/` over
soundfile). Zero-egress image ships no codecs, so this backend speaks WAV
only, via the stdlib `wave` module — 16/32-bit PCM in, float32 [-1, 1]
tensors out."""

from __future__ import annotations

import wave

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["load", "save", "info", "list_available_backends",
           "get_current_backend"]


def list_available_backends():
    return ["wave"]


def get_current_backend():
    return "wave"


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """-> (waveform Tensor [channels, frames] (channels_first) or
    [frames, channels], sample_rate)."""
    with wave.open(str(filepath), "rb") as w:
        sr = w.getframerate()
        n_channels = w.getnchannels()
        width = w.getsampwidth()
        w.setpos(frame_offset)
        n = num_frames if num_frames > 0 else w.getnframes() - frame_offset
        raw = w.readframes(n)
    dtype = {1: np.uint8, 2: np.int16, 4: np.int32}.get(width)
    if dtype is None:
        raise ValueError(f"unsupported PCM sample width {width}")
    data = np.frombuffer(raw, dtype).reshape(-1, n_channels)
    if normalize:
        if width == 1:  # unsigned 8-bit
            data = (data.astype(np.float32) - 128.0) / 128.0
        else:
            data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    out = data.T if channels_first else data
    return Tensor(np.ascontiguousarray(out)), sr


def save(filepath, src, sample_rate, channels_first=True,
         bits_per_sample=16):
    data = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
    if channels_first:
        data = data.T  # -> [frames, channels]
    if bits_per_sample != 16:
        raise ValueError("only 16-bit PCM save is supported")
    pcm = np.clip(data, -1.0, 1.0)
    pcm = (pcm * 32767.0).astype(np.int16)
    with wave.open(str(filepath), "wb") as w:
        w.setnchannels(pcm.shape[1] if pcm.ndim > 1 else 1)
        w.setsampwidth(2)
        w.setframerate(int(sample_rate))
        w.writeframes(np.ascontiguousarray(pcm).tobytes())


def info(filepath):
    with wave.open(str(filepath), "rb") as w:
        return {"sample_rate": w.getframerate(),
                "num_frames": w.getnframes(),
                "num_channels": w.getnchannels(),
                "bits_per_sample": 8 * w.getsampwidth()}
