"""Audio functional utilities (reference:
`python/paddle/audio/functional/functional.py` — mel scale, fbank matrix,
dct, power_to_db; `window.py` — get_window)."""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def hz_to_mel(freq, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
    freq = np.asarray(freq, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(freq >= min_log_hz,
                    min_log_mel + np.log(np.maximum(freq, 1e-10)
                                         / min_log_hz) / logstep, mels)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    mel = np.asarray(mel, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(mel >= min_log_mel,
                    min_log_hz * np.exp(logstep * (mel - min_log_mel)),
                    freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return Tensor(jnp.asarray(mel_to_hz(mels, htk), dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2] (reference
    compute_fbank_matrix)."""
    f_max = f_max if f_max is not None else sr / 2.0
    fftfreqs = np.linspace(0, sr / 2, 1 + n_fft // 2)
    melpts = mel_to_hz(np.linspace(hz_to_mel(f_min, htk),
                                   hz_to_mel(f_max, htk), n_mels + 2), htk)
    fdiff = np.diff(melpts)
    ramps = melpts[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melpts[2:n_mels + 2] - melpts[:n_mels])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb, dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10 with clamping (reference power_to_db)."""
    x = spect._data if isinstance(spect, Tensor) else jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
    log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc] (reference create_dct)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(math.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct.T, dtype))


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """hann/hamming/blackman/bartlett/bohman/rect (reference window.py)."""
    name = window if isinstance(window, str) else window[0]
    n = win_length + (0 if fftbins else -1)
    t = np.arange(win_length)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * math.pi * t / max(n, 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * math.pi * t / max(n, 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * math.pi * t / max(n, 1))
             + 0.08 * np.cos(4 * math.pi * t / max(n, 1)))
    elif name == "bartlett":
        w = 1.0 - np.abs(2 * t / max(n, 1) - 1.0)
    elif name == "bohman":
        x = np.abs(2 * t / max(n, 1) - 1.0)
        w = (1 - x) * np.cos(math.pi * x) + np.sin(math.pi * x) / math.pi
    elif name in ("rect", "boxcar", "ones"):
        w = np.ones(win_length)
    else:
        raise ValueError(f"unsupported window {name!r}")
    return Tensor(jnp.asarray(w, dtype))
