"""paddle.Model high-level API (reference: `python/paddle/hapi/model.py:1472` fit).

Two execution modes:
  - eager: per-op dispatch with tape autograd (debuggable, the default UX)
  - compiled (default when shapes are static): the whole
    forward+loss+backward+optimizer step is functionalized
    (`paddle_tpu.jit.functionalize`) and compiled by XLA into one program —
    the TPU analogue of the reference's executor path (`pir_interpreter.cc:1492`),
    with the optimizer update fused in (analogue of fused `_C_ops.adamw_`).
"""

import time

import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.hapi.callbacks import config_callbacks
from paddle_tpu.metric import Metric


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = shape
        self.dtype = dtype
        self.name = name


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self._compiled_step = None
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        return self

    # -- single-step APIs ----------------------------------------------------
    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            raise RuntimeError("call prepare(loss=...) first")
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        lbls = labels if isinstance(labels, (list, tuple)) else [labels]
        if callable(self._loss) and not hasattr(self._loss, "forward"):
            return self._loss(*outs, *lbls)
        return self._loss(outs[0], lbls[0])

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [i if isinstance(i, Tensor) else Tensor(np.asarray(i)) for i in ins]
        outputs = self.network(*ins)
        loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._eval_metrics(outputs, labels)
        return [loss.numpy()], metrics if metrics else [loss.numpy()]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [i if isinstance(i, Tensor) else Tensor(np.asarray(i)) for i in ins]
        outputs = self.network(*ins)
        loss = self._compute_loss(outputs, labels)
        metrics = self._eval_metrics(outputs, labels)
        return [loss.numpy()], metrics

    def predict_batch(self, inputs):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [i if isinstance(i, Tensor) else Tensor(np.asarray(i)) for i in ins]
        outputs = self.network(*ins)
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        return [o.numpy() for o in outs]

    def _eval_metrics(self, outputs, labels):
        res = []
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        lbls = labels if isinstance(labels, (list, tuple)) else [labels]
        for m in self._metrics:
            c = m.compute(outs[0], lbls[0])
            res.append(m.update(c))
        return res

    # -- fit loop ------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, nan_action="warn"):
        from paddle_tpu.io import DataLoader, Dataset
        from paddle_tpu.observability import TrainingMonitor

        # per-step telemetry (wall time, samples/sec, HBM high-water, the
        # NaN/inf loss action) into the shared registry; train_batch already
        # reads the loss back to host each step, so the check adds no sync
        self._monitor = TrainingMonitor(source="hapi_fit",
                                        nan_action=nan_action)

        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                                      drop_last=drop_last, num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        else:
            eval_loader = eval_data

        do_eval = eval_loader is not None
        steps = len(train_loader) if hasattr(train_loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs, steps=steps,
                                log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
                                verbose=verbose, metrics=self._metrics_name())

        self.stop_training = False
        cbks.on_begin("train")
        logs = {}
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            logs = self._run_one_epoch(train_loader, cbks, "train", num_iters=num_iters)
            cbks.on_epoch_end(epoch, logs)

            if do_eval and epoch % eval_freq == 0:
                eval_steps = len(eval_loader) if hasattr(eval_loader, "__len__") else None
                cbks.on_begin("eval", {"steps": eval_steps, "metrics": self._metrics_name()})
                eval_logs = self._run_one_epoch(eval_loader, cbks, "eval")
                cbks.on_end("eval", eval_logs)
        cbks.on_end("train", logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0,
                 callbacks=None, num_iters=None):
        from paddle_tpu.io import DataLoader, Dataset

        if isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size, num_workers=num_workers)
        else:
            eval_loader = eval_data
        self._reset_metrics()
        cbks = config_callbacks(callbacks, model=self, log_freq=log_freq, verbose=verbose,
                                metrics=self._metrics_name())
        eval_steps = len(eval_loader) if hasattr(eval_loader, "__len__") else None
        cbks.on_begin("eval", {"steps": eval_steps, "metrics": self._metrics_name()})
        logs = self._run_one_epoch(eval_loader, cbks, "eval")
        cbks.on_end("eval", logs)
        result = {"loss": logs.get("loss")}
        for m in self._metrics:
            res = m.accumulate()
            names = m.name() if isinstance(m.name(), (list, tuple)) else [m.name()]
            vals = res if isinstance(res, (list, tuple)) else [res]
            for n, v in zip(names, vals):
                result[n] = v
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        from paddle_tpu.io import DataLoader, Dataset

        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size, num_workers=num_workers)
        else:
            loader = test_data
        outputs = []
        n_in = len(self._inputs) if self._inputs else 1
        for data in loader:
            data = data if isinstance(data, (list, tuple)) else [data]
            outs = self.predict_batch(data[:n_in])
            outputs.append(outs)
        # transpose: list-of-batches-of-outputs -> list-of-outputs
        n_out = len(outputs[0])
        merged = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            merged = [np.vstack(m) for m in merged]
        return merged

    def _run_one_epoch(self, loader, cbks, mode, num_iters=None):
        logs = {}
        self._reset_metrics()
        # sample-weighted running mean, matching the reference ProgBarLogger's
        # averaged loss (reference python/paddle/hapi/model.py _run_one_epoch)
        loss_sum, seen = 0.0, 0
        for step, data in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            cbks.on_batch_begin(mode, step, logs)
            data = data if isinstance(data, (list, tuple)) else [data]
            n_in = len(self._inputs) if self._inputs else 1
            ins, lbls = data[:n_in], data[n_in:]
            monitor = getattr(self, "_monitor", None) if mode == "train" \
                else None
            if mode == "train":
                t0 = time.perf_counter() if monitor else None
                losses, metrics = self.train_batch(ins, lbls)
                step_wall = (time.perf_counter() - t0) if monitor else None
            elif mode == "eval":
                losses, metrics = self.eval_batch(ins, lbls)
            else:
                self.predict_batch(ins)
                losses, metrics = [np.zeros(1)], []
            batch0 = ins[0]
            bsz = batch0.shape[0] if hasattr(batch0, "shape") else 1
            batch_loss = float(np.asarray(losses[0]).reshape(-1)[0])
            if monitor is not None:
                monitor.record_step(step_wall, loss_value=batch_loss,
                                    samples=bsz)
            loss_sum += batch_loss * bsz
            seen += bsz
            logs["loss"] = loss_sum / max(seen, 1)
            logs["batch_loss"] = batch_loss
            logs["step"] = step
            logs["batch_size"] = bsz
            self._merge_metric_logs(logs)
            cbks.on_batch_end(mode, step, logs)
        return logs

    def _merge_metric_logs(self, logs):
        for m in self._metrics:
            res = m.accumulate()
            names = m.name() if isinstance(m.name(), (list, tuple)) else [m.name()]
            vals = res if isinstance(res, (list, tuple)) else [res]
            for n, v in zip(names, vals):
                logs[n] = v

    def _reset_metrics(self):
        for m in self._metrics:
            m.reset()

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names += n if isinstance(n, (list, tuple)) else [n]
        return names

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        from paddle_tpu.framework.io import save as psave

        if training:
            psave(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                psave(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from paddle_tpu import jit

            jit.save(self.network, path)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from paddle_tpu.framework.io import load as pload

        state = pload(path + ".pdparams")
        self.network.set_state_dict(state)
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        n_params = sum(p.size for p in self.network.parameters())
        trainable = sum(p.size for p in self.network.parameters() if not p.stop_gradient)
        summary_str = (f"Total params: {n_params}\n"
                       f"Trainable params: {trainable}\n"
                       f"Non-trainable params: {n_params - trainable}\n")
        print(summary_str)
        return {"total_params": n_params, "trainable_params": trainable}
