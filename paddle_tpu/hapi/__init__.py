from paddle_tpu.hapi.model import Model, InputSpec  # noqa: F401
from paddle_tpu.hapi import callbacks  # noqa: F401
from paddle_tpu.hapi.callbacks import Callback, ProgBarLogger, ModelCheckpoint, EarlyStopping  # noqa: F401


def summary(net, input_size=None, dtypes=None):
    n_params = sum(p.size for p in net.parameters())
    print(f"Total params: {n_params}")
    return {"total_params": n_params}
