"""hapi callbacks (reference: `python/paddle/hapi/callbacks.py`)."""

import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda logs=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda logs=None: None)(logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin", lambda step, logs=None: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end", lambda step, logs=None: None)(step, logs)

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def append(self, cbk):
        self.callbacks.append(cbk)

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def on_begin(self, mode, logs=None):
        for c in self.callbacks:
            c.on_begin(mode, logs)

    def on_end(self, mode, logs=None):
        for c in self.callbacks:
            c.on_end(mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_begin(mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_end(mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self.epochs = None
        self.epoch = 0

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._start = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose == 2 and step % self.log_freq == 0:
            items = [f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                     for k, v in (logs or {}).items() if k not in ("step", "batch_size")]
            steps = self.params.get("steps")
            print(f"step {step + 1}/{steps if steps else '?'} - " + " - ".join(items))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dur = time.time() - self._start
            items = [f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                     for k, v in (logs or {}).items() if k not in ("step", "batch_size")]
            print(f"Epoch {epoch + 1} done ({dur:.1f}s) - " + " - ".join(items))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and self.model and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir and self.model:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_eval_end(self, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            return
        current = float(np.asarray(current).reshape(-1)[0])
        improved = (self.best is None or
                    (self.mode == "min" and current < self.best - self.min_delta) or
                    (self.mode == "max" and current > self.best + self.min_delta))
        if improved:
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience and self.model is not None:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from paddle_tpu.optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        if opt and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None, metrics=None,
                     mode="train"):
    cbks = callbacks if isinstance(callbacks, (list, tuple)) else ([callbacks] if callbacks else [])
    cbks = list(cbks)
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    cbk_list.set_params({"batch_size": batch_size, "epochs": epochs, "steps": steps,
                         "verbose": verbose, "metrics": metrics or ["loss"]})
    return cbk_list
