"""Paged-KV serving engine: block tables + hash-based prefix reuse.

`Engine` (serving/engine.py) reserves a full `max_len` KV stripe per
slot, so HBM — not compute — caps concurrency, and identical system
prompts are re-prefilled for every request. `PagedEngine` replaces the
stripes with the vLLM PagedAttention memory model (Kwon et al.,
SOSP'23) plus SGLang-style prefix sharing, on the same iteration-level
scheduler:

  - ONE fixed page pool `[L, num_pages, nkv, page_size, hd]` (heads-major
    pages — the layout the Pallas paged decode kernel consumes) and a
    per-slot BLOCK TABLE mapping sequence positions to pages. A request
    occupies ceil(len/page_size) pages, not max_len — the fragmentation
    the stripe engine wastes becomes admission headroom;
  - PREFIX CACHE: full pages of every prefilled prompt are registered in
    `BlockAllocator`'s exact-match hash chain. A new request walks the
    chain, REFS the hit pages (shared, refcounted — the bytes exist
    once), and prefills only the remaining suffix: a shared system
    prompt is computed once, then every later request starts decoding
    after a block-table lookup;
  - PREFILL = gather the hit pages into a contiguous scratch stripe,
    run the suffix forward at position h (one program per suffix-length
    bucket — the compile-count discipline of the stripe engine), scatter
    the freshly computed pages back into the pool;
  - DECODE = one batched paged step (`generation._paged_forward_decode`,
    the traced body behind the public `generation.paged_decode_step`):
    per-row scatter of the new k/v into each slot's tail page, attention
    gathered through the block tables (per-row page-index prefetch in
    the Pallas kernel). The host allocates a tail page exactly when a
    row's position crosses a page boundary, and `ensure_writable` COWs
    any page that is shared or hash-registered before it is written;
  - ADMISSION reserves the request's worst-case page count
    (`scheduler.pages_for` minus prefix hits) so FIFO requests always
    finish without preemption; when the pool (free + LRU-evictable
    cached pages) can't cover the queue head, the engine decodes instead
    and admits later.

Greedy parity with the stripe engine and sequential `generate` is exact:
pages in table order ARE the contiguous cache (gathering them reproduces
the stripe bit-for-bit), padded-softmax tails underflow to exact zeros,
and int8 `quantize_params` trees stream through the same fused
dequant-matmul dispatch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import generation as gen
from paddle_tpu.models import llama_functional as lf
from paddle_tpu.serving.block_manager import NULL_PAGE, BlockAllocator
from paddle_tpu.serving.engine import Engine, Request
from paddle_tpu.serving.scheduler import bucket_for, pages_for

__all__ = ["PagedEngine"]


def _paged_prefill_traced(params, ids, h, last_idx, bt_row, new_pages,
                          pk, pv, cos, sin, *, args, metrics, page_size,
                          pages_per_slot):
    """Prefill a request whose first `h` positions are already cached:
    gather the slot's pages into a contiguous scratch stripe, forward the
    SUFFIX tokens at position h, scatter the freshly written pages back.

    ids: [1, sb] suffix right-padded to a length bucket; h: traced token
    count covered by prefix hits (a page multiple); last_idx: index of the
    prompt's true last token WITHIN the suffix block (n - 1 - h);
    bt_row/new_pages: [P] page indices (unused entries -> null page 0).
    One XLA program per suffix bucket — h, last_idx and the page vectors
    are traced operands, so hit depth never recompiles."""
    metrics.inc("prefill_compiles")
    L, nkv, hd = pk.shape[0], pk.shape[2], pk.shape[4]
    ps, P = page_size, pages_per_slot
    sb = ids.shape[1]
    dtype = pk.dtype

    # gather the block-table row into contiguous [L, 1, nkv, P*ps, hd]
    # (hit pages carry real prefix K/V; later entries are garbage that the
    # suffix writes + position mask keep unread), then pad by the suffix
    # bucket so the write at [h, h+sb) can never clamp
    g_k = jnp.swapaxes(pk[:, bt_row], 1, 2).reshape(L, 1, nkv, P * ps, hd)
    g_v = jnp.swapaxes(pv[:, bt_row], 1, 2).reshape(L, 1, nkv, P * ps, hd)
    pad = jnp.zeros((L, 1, nkv, sb, hd), dtype)
    temp_k = jnp.concatenate([g_k, pad], axis=3)
    temp_v = jnp.concatenate([g_v, pad], axis=3)

    logits, temp_k, temp_v = gen._forward_cached(
        params, ids, temp_k, temp_v, h, cos, sin, args, last_idx=last_idx)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]

    # scatter the newly computed pages (suffix positions [h + i*ps, ...))
    # into the pool; unused entries land on the null page
    def chunk(t, i):
        return jax.lax.dynamic_slice_in_dim(t, h + i * ps, ps, axis=3)

    new_k = jnp.concatenate([chunk(temp_k, i) for i in range(P)], axis=1)
    new_v = jnp.concatenate([chunk(temp_v, i) for i in range(P)], axis=1)
    pk = pk.at[:, new_pages].set(new_k)   # [L, P, nkv, ps, hd]
    pv = pv.at[:, new_pages].set(new_v)
    return pk, pv, first


def _paged_decode_traced(params, tokens, pk, pv, bt, pos, cos, sin, *,
                         args, metrics, page_size):
    metrics.inc("decode_compiles")
    logits, pk, pv = gen._paged_forward_decode(
        params, tokens[:, None], pk, pv, bt, pos, cos, sin, args, page_size)
    return pk, pv, jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _copy_page_traced(pk, pv, src, dst):
    """Device half of copy-on-write: clone one page's K/V across layers."""
    pk = jax.lax.dynamic_update_slice_in_dim(
        pk, jax.lax.dynamic_slice_in_dim(pk, src, 1, axis=1), dst, axis=1)
    pv = jax.lax.dynamic_update_slice_in_dim(
        pv, jax.lax.dynamic_slice_in_dim(pv, src, 1, axis=1), dst, axis=1)
    return pk, pv


class PagedEngine(Engine):
    """Continuous-batching engine over a paged KV cache with prefix reuse.

    page_size: tokens per KV page. On TPU keep it a multiple of 16 (bf16
               sublane tile) with head_dim a multiple of 128 so the Pallas
               paged decode kernel stays eligible; it is also the prefix-
               cache granularity (only full pages are shared).
    num_pages: pool size INCLUDING the reserved null page 0. Defaults to
               max_slots * (max_len/page_size) + 1 — the stripe engine's
               capacity; set it lower to oversubscribe slots against the
               real (sub-max_len, prefix-shared) footprint, which is the
               entire point.
    max_len:   per-REQUEST cap (block tables hold max_len/page_size
               entries); no longer a per-slot HBM reservation.
    """

    def __init__(self, params, args, *, max_slots=4, max_len=256,
                 page_size=16, num_pages=None, min_bucket=16, pad_id=0,
                 metrics=None):
        if max_len % page_size != 0:
            raise ValueError(
                f"max_len={max_len} must be a multiple of "
                f"page_size={page_size}")
        self.page_size = int(page_size)
        self.pages_per_slot = int(max_len) // self.page_size
        self.num_pages = (int(num_pages) if num_pages is not None
                          else int(max_slots) * self.pages_per_slot + 1)
        super().__init__(params, args, max_slots=max_slots, max_len=max_len,
                         min_bucket=min_bucket, pad_id=pad_id,
                         metrics=metrics)

    def _setup_device_state(self):
        args = self.args
        L = lf.stack_leading_dim(self.params["layers"])
        hd = args.hidden_size // args.num_heads
        dtype = self.params["embedding"].dtype
        self._pk = jnp.zeros(
            (L, self.num_pages, args.num_kv_heads, self.page_size, hd),
            dtype)
        self._pv = jnp.zeros_like(self._pk)
        # 2*max_len: suffix prefills write at [h, h+bucket), which can
        # overshoot max_len before masking trims it
        self._cos, self._sin = lf.rope_tables(2 * self.max_len, hd,
                                              args.rope_theta)

        self._alloc = BlockAllocator(self.num_pages, self.page_size,
                                     metrics=self.metrics)
        self._bt = [[] for _ in range(self.max_slots)]   # host block tables
        self._resv = {}            # slot -> pages still reserved for decode
        self._reserved_total = 0

        donate = jax.default_backend() == "tpu"
        self._prefill = jax.jit(
            functools.partial(_paged_prefill_traced, args=args,
                              metrics=self.metrics,
                              page_size=self.page_size,
                              pages_per_slot=self.pages_per_slot),
            donate_argnums=(6, 7) if donate else ())
        self._decode = jax.jit(
            functools.partial(_paged_decode_traced, args=args,
                              metrics=self.metrics,
                              page_size=self.page_size),
            donate_argnums=(2, 3) if donate else ())
        self._copy_page = jax.jit(
            _copy_page_traced, donate_argnums=(0, 1) if donate else ())

    # -- admission ----------------------------------------------------------
    def submit(self, req):
        if not isinstance(req, Request):
            req = Request(req)
        need = pages_for(req.prompt_ids.size, req.max_new_tokens,
                         self.page_size)
        if need > self._alloc.capacity:
            raise ValueError(
                f"request needs {need} KV pages but the pool only has "
                f"{self._alloc.capacity} (num_pages={self.num_pages}, "
                f"page_size={self.page_size})")
        return super().submit(req)

    def _can_prefill(self):
        if not (self.queue and self.slots.free_count):
            return False
        req = self.queue.peek()
        hits = self._alloc.match_prefix(req.prompt_ids, commit=False)
        # reviving a cached (refcount-0) hit consumes availability just
        # like a fresh alloc; an actively shared hit is free
        revive = sum(1 for p in hits if self._alloc.refcount(p) == 0)
        need = (pages_for(req.prompt_ids.size, req.max_new_tokens,
                          self.page_size) - len(hits) + revive)
        return need <= self._alloc.available - self._reserved_total

    # -- prefill ------------------------------------------------------------
    def _prefill_device(self, req, slot, n):
        ps, P = self.page_size, self.pages_per_slot
        hits = self._alloc.match_prefix(req.prompt_ids)   # refs hit pages
        h = len(hits) * ps
        n_now = -(-n // ps) - len(hits)                   # pages to write
        new_pages = [self._alloc.alloc() for _ in range(n_now)]
        pages = hits + new_pages
        resv = pages_for(n, req.max_new_tokens, ps) - len(pages)
        self._resv[slot] = resv
        self._reserved_total += resv
        self._bt[slot] = pages

        bt_row = np.zeros(P, np.int32)
        bt_row[:len(pages)] = pages
        new_vec = np.full(P, NULL_PAGE, np.int32)
        new_vec[:n_now] = new_pages
        sb = bucket_for(n - h, self.min_bucket, self.max_len)
        padded = np.full((1, sb), self.pad_id, np.int32)
        padded[0, :n - h] = req.prompt_ids[h:]
        with self.metrics.timer("prefill_s"):
            self._pk, self._pv, first = self._prefill(
                self.params, jnp.asarray(padded), jnp.int32(h),
                jnp.int32(n - 1 - h), jnp.asarray(bt_row),
                jnp.asarray(new_vec), self._pk, self._pv,
                self._cos, self._sin)
            first = int(first)
        # make this prompt's full pages hittable for future requests
        self._alloc.register_prefix(req.prompt_ids, pages[:n // ps])
        self.metrics.inc("prompt_tokens", n)
        self.metrics.inc("prefix_tokens_hit", h)
        self.metrics.inc("prefix_pages_hit", len(hits))
        self.metrics.inc("prefix_pages_queried", (n - 1) // ps)
        return sb, first

    # -- decode -------------------------------------------------------------
    def _decode_device(self, active):
        ps, P = self.page_size, self.pages_per_slot
        for slot in active:
            pi = int(self._npos[slot]) // ps
            pages = self._bt[slot]
            if pi == len(pages):
                # crossing a page boundary: draw the tail page from this
                # slot's admission-time reservation
                pages.append(self._alloc.alloc())
                self._resv[slot] -= 1
                self._reserved_total -= 1
            else:
                old = pages[pi]
                page, copied = self._alloc.ensure_writable(old)
                if copied:
                    self._pk, self._pv = self._copy_page(
                        self._pk, self._pv, jnp.int32(old), jnp.int32(page))
                    pages[pi] = page
        bt = np.full((self.max_slots, P), NULL_PAGE, np.int32)
        for slot in active:
            bt[slot, :len(self._bt[slot])] = self._bt[slot]
        with self.metrics.timer("decode_step_s"):
            self._pk, self._pv, nxt = self._decode(
                self.params, jnp.asarray(self._last_tok), self._pk,
                self._pv, jnp.asarray(bt), jnp.asarray(self._npos),
                self._cos, self._sin)
        return np.asarray(nxt)

    # -- lifecycle ----------------------------------------------------------
    def _retire(self, slot):
        for p in self._bt[slot]:
            self._alloc.release(p)
        self._bt[slot] = []
        self._reserved_total -= self._resv.pop(slot, 0)
        super()._retire(slot)

    def reset(self):
        """Forget all requests, block tables, AND the prefix cache (cold
        cache — a warm timed run after reset would be all hits and lie);
        compiled programs and compile counters survive."""
        super().reset()
        self._alloc = BlockAllocator(self.num_pages, self.page_size,
                                     metrics=self.metrics)
        self._bt = [[] for _ in range(self.max_slots)]
        self._resv = {}
        self._reserved_total = 0
