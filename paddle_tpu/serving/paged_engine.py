"""Paged-KV serving engine: block tables, prefix reuse, tensor-parallel
decode, chunked prefill, and speculative decoding.

`Engine` (serving/engine.py) reserves a full `max_len` KV stripe per
slot, so HBM — not compute — caps concurrency, and identical system
prompts are re-prefilled for every request. `PagedEngine` replaces the
stripes with the vLLM PagedAttention memory model (Kwon et al.,
SOSP'23) plus SGLang-style prefix sharing, on the same iteration-level
scheduler:

  - ONE fixed page pool `[L, num_pages, nkv, page_size, hd]` (heads-major
    pages — the layout the Pallas paged decode kernel consumes) and a
    per-slot BLOCK TABLE mapping sequence positions to pages;
  - PREFIX CACHE: every prefilled prompt is registered in
    `BlockAllocator`'s radix tree and REF'd by later requests sharing
    the prefix at TOKEN granularity (refcounted, COW-protected; a
    mid-page divergence shares the straddled page through a
    copy-on-write split — the PR-8 exact-match hash chain survives as
    `prefix_policy="hash"`, the bench baseline);
  - PREFILL = gather the hit pages, run the suffix forward at traced
    position h (one program per suffix-length bucket), scatter the new
    pages; DECODE = one batched paged step through the block tables;
  - ADMISSION reserves the worst-case page count minus hits and defers
    the FIFO head under page pressure.

On top of that scheduler this engine adds the three serving-throughput
levers (ROADMAP item 1):

TENSOR PARALLELISM (`mesh=`): pass a Mesh with an `mp` axis and every
step program runs as one shard_map SPMD program over it — weights in
the Megatron split, the page pool sharded on its nkv axis, block tables
and the host-side allocator untouched (`serving/tp.py` has the
placement; `mesh_utils.shard_map_compat` keeps legacy jax working).
Model size now scales with the mesh, not one chip's HBM.

CHUNKED PREFILL (`prefill_chunk=`): a long prompt no longer runs as one
monolithic program that stalls every decoding slot for its whole
duration. The suffix is split into page-aligned chunks and the
scheduler INTERLEAVES: chunk, then a decode step (or a short prefill),
then the next chunk — so TTFT for queued requests stays flat under
long-prompt bursts. Chunks reuse the suffix-bucket prefill program
(each chunk is "a suffix at a deeper h"), composing with prefix hits
unchanged.

SPECULATIVE DECODING (`draft_params=`): a cheap draft model (e.g.
`generation.draft_from_params` truncation) proposes `spec_tokens`
greedy tokens in ONE traced scan over its own stripe cache; the target
model scores the whole window in ONE batched paged verify forward; the
host commits the longest exactly-matching prefix plus the target's own
next token (Leviathan-style greedy acceptance — output is token-for-
token THE target's greedy sequence, just cheaper). Accepted tokens'
K/V land in the paged tail pages during verify; rejected positions are
garbage that the write-before-attend order overwrites, and positions
past a row's page reservation are redirected to the null page.

Greedy parity with sequential `generate` stays exact under every
combination of the three (and int8 `quantize_params` trees stream
through the same fused dequant-matmul dispatch).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.models import generation as gen
from paddle_tpu.models import llama_functional as lf
from paddle_tpu.serving.block_manager import NULL_PAGE, BlockAllocator
from paddle_tpu.serving.engine import Engine, Request
from paddle_tpu.serving.sampler import pick as _pick
from paddle_tpu.serving.scheduler import bucket_for, pages_for
from paddle_tpu.serving.spec_decode import SpecDecoder

__all__ = ["PagedEngine"]


def _paged_prefill_traced(params, ids, h, last_idx, bt_row, new_pages,
                          pk, pv, cos, sin, temp, top_p, top_k, seeds, *,
                          args, metrics, page_size, pages_per_slot,
                          sample=False, tp_axis=None, tp_degree=1):
    """Prefill a suffix window whose first `h` positions are already
    cached: gather the slot's pages into a contiguous scratch stripe,
    forward the window tokens at position h, scatter the freshly written
    pages back.

    ids: [1, sb] window right-padded to a length bucket; h: traced token
    count already cached (prefix hits AND previously prefilled chunks —
    TOKEN-granular under the radix cache, so h may sit mid-page: the
    straddled page is gathered from the frozen cached page and the
    scatter rewrites the slot's COW copy of it from the page-aligned
    base); last_idx: index of the window's last real token WITHIN the
    block; bt_row/new_pages: [P] page indices (unused entries -> null
    page 0). One XLA program per window bucket — h, last_idx and the
    page vectors are traced operands, so neither hit depth nor chunk
    position recompiles."""
    metrics.inc("prefill_compiles")
    quantized = isinstance(pk, gen.QuantizedKVPage)
    arr = pk.q if quantized else pk
    L, nkv, hd = arr.shape[0], arr.shape[2], arr.shape[4]
    ps, Pn = page_size, pages_per_slot
    sb = ids.shape[1]
    dtype = params["embedding"].dtype if quantized else pk.dtype

    # gather the block-table row into contiguous [L, 1, nkv, P*ps, hd]
    # (hit pages carry real prefix K/V; later entries are garbage that the
    # suffix writes + position mask keep unread), then pad by the suffix
    # bucket so the write at [h, h+sb) can never clamp. An int8 pool
    # dequantizes in the gather — the scratch stripe the forward runs
    # over is always the compute dtype
    if quantized:
        def dq(pool):
            raw = pool.q[:, bt_row].astype(jnp.float32)   # [L, P, nkv, ps, hd]
            sc = (pool.scale[:, bt_row] / 127.0)[..., None, None]
            return (raw * sc).astype(dtype)

        g_k = jnp.swapaxes(dq(pk), 1, 2).reshape(L, 1, nkv, Pn * ps, hd)
        g_v = jnp.swapaxes(dq(pv), 1, 2).reshape(L, 1, nkv, Pn * ps, hd)
    else:
        g_k = jnp.swapaxes(pk[:, bt_row], 1, 2).reshape(
            L, 1, nkv, Pn * ps, hd)
        g_v = jnp.swapaxes(pv[:, bt_row], 1, 2).reshape(
            L, 1, nkv, Pn * ps, hd)
    pad = jnp.zeros((L, 1, nkv, sb, hd), dtype)
    temp_k = jnp.concatenate([g_k, pad], axis=3)
    temp_v = jnp.concatenate([g_v, pad], axis=3)

    logits, temp_k, temp_v = gen._forward_cached(
        params, ids, temp_k, temp_v, h, cos, sin, args, last_idx=last_idx,
        tp_axis=tp_axis, tp_degree=tp_degree)
    # the emitted token sits at sequence index h + last_idx + 1 — the
    # (seed, position) the offline generate(seeds=...) would use
    first = _pick(logits, sample, temp, top_p, top_k, seeds,
                  h + last_idx + 1)[0]

    # scatter the freshly written pages back from the page-aligned base
    # below h: when h is mid-page the first chunk carries the gathered
    # cached half [base, h) plus the new tokens — exactly the COW-copy
    # content. Unused entries land on the null page.
    base = h - h % ps
    def chunk(t, i):
        return jax.lax.dynamic_slice_in_dim(t, base + i * ps, ps, axis=3)

    new_k = jnp.concatenate([chunk(temp_k, i) for i in range(Pn)], axis=1)
    new_v = jnp.concatenate([chunk(temp_v, i) for i in range(Pn)], axis=1)
    if quantized:
        # scatter-time quantization: per-(page, kv-head) absmax over the
        # VALID positions only — the scratch stripe beyond the window's
        # last real token [end = h + last_idx + 1] is garbage (pad +
        # forward junk) that would otherwise inflate the scale and crush
        # the real values' precision. Masked positions store 0.
        end = h + last_idx + 1
        pos_abs = (base + (jnp.arange(Pn, dtype=jnp.int32) * ps)[:, None]
                   + jnp.arange(ps, dtype=jnp.int32)[None, :])   # [Pn, ps]
        valid = (pos_abs < end)[None, :, None, :, None]

        def quant(newx):
            x = jnp.where(valid, newx.astype(jnp.float32), 0.0)
            s = jnp.max(jnp.abs(x), axis=(3, 4))                 # [L, Pn, nkv]
            qx = jnp.clip(jnp.round(
                x / jnp.maximum(s, 1e-9)[..., None, None] * 127.0),
                -127, 127).astype(jnp.int8)
            return qx, s

        qk, sk = quant(new_k)
        qv, sv = quant(new_v)
        pk = gen.QuantizedKVPage(pk.q.at[:, new_pages].set(qk),
                                 pk.scale.at[:, new_pages].set(sk))
        pv = gen.QuantizedKVPage(pv.q.at[:, new_pages].set(qv),
                                 pv.scale.at[:, new_pages].set(sv))
    else:
        pk = pk.at[:, new_pages].set(new_k)   # [L, P, nkv, ps, hd]
        pv = pv.at[:, new_pages].set(new_v)
    return pk, pv, first


def _paged_decode_traced(params, tokens, pk, pv, bt, pos, cos, sin, temp,
                         top_p, top_k, seeds, *, args, metrics, page_size,
                         sample=False, tp_axis=None, tp_degree=1):
    metrics.inc("decode_compiles")
    logits, pk, pv = gen._paged_forward_decode(
        params, tokens[:, None], pk, pv, bt, pos, cos, sin, args, page_size,
        tp_axis=tp_axis, tp_degree=tp_degree)
    return pk, pv, _pick(logits, sample, temp, top_p, top_k, seeds, pos + 1)


def _copy_page_traced(pk, pv, src, dst):
    """Device half of copy-on-write: clone one page's K/V across layers.
    The page axis is axis 1 of every pool leaf — the bf16 arrays AND both
    halves of an int8 `QuantizedKVPage` (codes [L, pages, ...] and scales
    [L, pages, nkv]) — so one tree_map covers both pool layouts."""
    def cp(a):
        return jax.lax.dynamic_update_slice_in_dim(
            a, jax.lax.dynamic_slice_in_dim(a, src, 1, axis=1), dst, axis=1)

    return (jax.tree_util.tree_map(cp, pk), jax.tree_util.tree_map(cp, pv))


class PagedEngine(Engine):
    """Continuous-batching engine over a paged KV cache with prefix
    reuse, optional tensor parallelism, chunked prefill, and speculative
    decoding.

    page_size: tokens per KV page. On TPU keep it a multiple of 16 (bf16
               sublane tile) with head_dim a multiple of 128 so the Pallas
               paged decode kernel stays eligible. Prefix sharing itself
               is TOKEN-granular (radix cache); page_size only sets the
               COW-copy unit a mid-page divergence pays for.
    num_pages: pool size INCLUDING the reserved null page 0. Defaults to
               max_slots * (max_len/page_size) + 1 — the stripe engine's
               capacity; set it lower to oversubscribe slots against the
               real (sub-max_len, prefix-shared) footprint, which is the
               entire point.
    max_len:   per-REQUEST cap (block tables hold max_len/page_size
               entries); no longer a per-slot HBM reservation.
    mesh:      optional jax Mesh carrying `tp_axis` (default 'mp'):
               weights and the page pool shard over it and every step
               program runs SPMD (serving/tp.py placement). num_kv_heads,
               num_heads and intermediate_size must divide the degree.
    prefill_chunk: optional chunk length (a multiple of page_size).
               Prompt suffixes longer than this prefill in chunks
               interleaved with decode steps — long prompts stop
               stalling in-flight requests.
    draft_params/draft_args: optional draft model (same vocab; e.g.
               `generation.draft_from_params`) enabling speculative
               decoding with `spec_tokens` drafts per round. Greedy
               requests only (exact-match acceptance); sampling requests
               are rejected at submit.
    kv_dtype:  None (pool in the model dtype) or 'int8' — quantize the
               KV page pool to int8 with per-(page, kv-head) absmax
               scales (`generation.QuantizedKVPage`). Prefill scatters
               quantize whole pages, decode/verify writes keep a RUNNING
               absmax (re-scaling a page's codes in-registers when a new
               token exceeds its scale), and attention dequantizes
               inside the paged kernel — KV bytes halve vs bf16, so an
               equal-HBM pool holds ~2x the pages. Outputs track the
               bf16 pool to a top-1 agreement bar, not bit-exactly
               (quantization perturbs KV); on TPU the int8 paged kernel
               needs page_size % 32 == 0 and head_dim % 128 == 0, other
               shapes ride the dequant-gather fallback.
    """

    def __init__(self, params, args, *, max_slots=4, max_len=256,
                 page_size=16, num_pages=None, min_bucket=16, pad_id=0,
                 metrics=None, mesh=None, tp_axis="mp", prefill_chunk=None,
                 draft_params=None, draft_args=None, spec_tokens=4,
                 donate_steps=None, prefix_policy="radix", kv_dtype=None):
        if prefix_policy not in ("radix", "hash"):
            raise ValueError(f"prefix_policy={prefix_policy!r} must be "
                             "'radix' or 'hash'")
        self.prefix_policy = prefix_policy
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"kv_dtype={kv_dtype!r} must be None (the "
                             "model dtype) or 'int8'")
        self.kv_dtype = kv_dtype
        if max_len % page_size != 0:
            raise ValueError(
                f"max_len={max_len} must be a multiple of "
                f"page_size={page_size}")
        self.page_size = int(page_size)
        self.pages_per_slot = int(max_len) // self.page_size
        self.num_pages = (int(num_pages) if num_pages is not None
                          else int(max_slots) * self.pages_per_slot + 1)
        self.mesh = mesh
        self.tp_axis = tp_axis
        if prefill_chunk is not None:
            prefill_chunk = int(prefill_chunk)
            if prefill_chunk < 1 or prefill_chunk % self.page_size:
                raise ValueError(
                    f"prefill_chunk={prefill_chunk} must be a positive "
                    f"multiple of page_size={page_size}")
        self.prefill_chunk = prefill_chunk
        if draft_params is not None and draft_args is None:
            raise ValueError("draft_params requires draft_args "
                             "(see generation.draft_from_params)")
        self.draft_params = draft_params
        self.draft_args = draft_args
        self.spec_tokens = int(spec_tokens)
        if draft_params is not None:
            if self.spec_tokens < 1:
                raise ValueError("spec_tokens must be >= 1")
            if draft_args.vocab_size != args.vocab_size:
                raise ValueError("draft and target must share a vocab")
        super().__init__(params, args, max_slots=max_slots, max_len=max_len,
                         min_bucket=min_bucket, pad_id=pad_id,
                         metrics=metrics, donate_steps=donate_steps)

    @property
    def spec_enabled(self):
        return self.draft_params is not None

    # -- program construction ----------------------------------------------
    def _sharded(self, body, in_specs, out_specs, donate):
        """jit a traced step body, shard_map-wrapped when a mesh is set.
        check_vma stays off for these forward-only programs: the legacy
        checker's value is guarding AD transposes, and serving has no
        gradients — while its missing rules for scatter/sort/PRNG
        primitives would reject valid inference bodies."""
        if self.mesh is None:
            return jax.jit(body, donate_argnums=donate)
        from paddle_tpu.distributed.mesh_utils import shard_map_compat

        sm = shard_map_compat(body, self.mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False)
        return jax.jit(sm, donate_argnums=donate)

    def _setup_device_state(self):
        args = self.args
        axis = self.tp_axis
        if self.mesh is not None:
            from paddle_tpu.serving import tp as tp_lib

            self.tp_degree = int(self.mesh.shape[axis])
            tp_lib.tp_validate(args, self.tp_degree)
            # eager placement: weights land in their Megatron shards once,
            # at construction — never resharded on the hot path
            self.params = tp_lib.shard_params(self.params, self.mesh, axis)
            self._pspecs = tp_lib.llama_tp_specs(self.params, axis)
            self._poolspec = tp_lib.pool_spec(axis)
        else:
            self.tp_degree = 1
            self._pspecs = self._poolspec = None
        tp_kw = dict(tp_axis=axis if self.mesh is not None else None,
                     tp_degree=self.tp_degree)

        L = lf.stack_leading_dim(self.params["layers"])
        hd = args.hidden_size // args.num_heads
        dtype = jax.tree_util.tree_leaves(self.params["embedding"])[0].dtype
        nkv = args.num_kv_heads
        pool_shape = (L, self.num_pages, nkv, self.page_size, hd)
        if self.kv_dtype == "int8":
            # int8 pages + per-(page, kv-head) absmax scales: halves (vs
            # bf16) the KV bytes behind a page, so the same HBM budget
            # holds ~2x the pages -> ~2x the sustained slots. Scales
            # start at 0: the first write into a page sets them
            self._pk = gen.QuantizedKVPage(
                jnp.zeros(pool_shape, jnp.int8),
                jnp.zeros((L, self.num_pages, nkv), jnp.float32))
            self._pv = gen.QuantizedKVPage(
                jnp.zeros(pool_shape, jnp.int8),
                jnp.zeros((L, self.num_pages, nkv), jnp.float32))
        else:
            self._pk = jnp.zeros(pool_shape, dtype)
            self._pv = jnp.zeros_like(self._pk)
        self.metrics.set_gauge("kv_pool_bytes", 2 * sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(self._pk)))
        if self.mesh is not None:
            # both halves of a QuantizedKVPage shard on nkv, so the bf16
            # pool spec applies to the pair as a pytree prefix
            sh = NamedSharding(self.mesh, self._poolspec)
            self._pk = jax.device_put(self._pk, sh)
            self._pv = jax.device_put(self._pv, sh)
        # 2*max_len: suffix prefills write at [h, h+bucket), which can
        # overshoot max_len before masking trims it
        self._cos, self._sin = lf.rope_tables(2 * self.max_len, hd,
                                              args.rope_theta)

        self._alloc = BlockAllocator(self.num_pages, self.page_size,
                                     metrics=self.metrics,
                                     policy=self.prefix_policy)
        self._bt = [[] for _ in range(self.max_slots)]   # host block tables
        self._resv = {}            # slot -> pages still reserved for decode
        self._reserved_total = 0
        self._chunk_streams = {}   # slot -> {req, n, done} mid-chunked-prefill
        self._chunk_turn = False
        self._admit_idx = None     # _can_prefill's cached admission scan

        donate = self._donate_enabled()
        rep = P()
        prefill_specs = dict(
            in_specs=(self._pspecs, rep, rep, rep, rep, rep,
                      self._poolspec, self._poolspec, rep, rep, rep, rep,
                      rep, rep),
            out_specs=(self._poolspec, self._poolspec, rep))
        decode_specs = dict(
            in_specs=(self._pspecs, rep, self._poolspec, self._poolspec,
                      rep, rep, rep, rep, rep, rep, rep, rep),
            out_specs=(self._poolspec, self._poolspec, rep))
        self._prefill_v, self._decode_v = {}, {}
        for sample in (False, True):
            self._prefill_v[sample] = self._sharded(
                functools.partial(
                    _paged_prefill_traced, args=args, metrics=self.metrics,
                    page_size=self.page_size,
                    pages_per_slot=self.pages_per_slot, sample=sample,
                    **tp_kw),
                donate=(6, 7) if donate else (), **prefill_specs)
            self._decode_v[sample] = self._sharded(
                functools.partial(
                    _paged_decode_traced, args=args, metrics=self.metrics,
                    page_size=self.page_size, sample=sample, **tp_kw),
                donate=(2, 3) if donate else (), **decode_specs)
        self._copy_page = self._sharded(
            _copy_page_traced,
            in_specs=(self._poolspec, self._poolspec, rep, rep),
            out_specs=(self._poolspec, self._poolspec),
            donate=(0, 1) if donate else ())

        # the speculative half (draft cache/programs + the sharded verify
        # program + the propose/verify/accept/roll-back round) lives in
        # serving/spec_decode.py
        self._spec = SpecDecoder(self, donate) if self.spec_enabled else None

    # -- admission ----------------------------------------------------------
    def submit(self, req):
        if not isinstance(req, Request):
            req = Request(req)
        need = pages_for(req.prompt_ids.size, req.max_new_tokens,
                         self.page_size)
        if need > self._alloc.capacity:
            raise ValueError(
                f"request needs {need} KV pages but the pool only has "
                f"{self._alloc.capacity} (num_pages={self.num_pages}, "
                f"page_size={self.page_size})")
        return super().submit(req)

    def _peek_hits(self, req):
        """Side-effect-free PrefixMatch for a queued request, memoized
        on the allocator's prefix_version: the anti-convoy scan below
        runs every step while a chunk stream is active, and re-walking
        every queued prompt each step is O(queue x prompt_len) host work
        for an answer that only changes when the prefix index does. Any
        registration, split, or eviction bumps prefix_version and
        invalidates the memo — a stale hit set here would skew the
        worst-case page reservation `_can_prefill` gates admission on."""
        ver = self._alloc.prefix_version
        cached = getattr(req, "_hits_memo", None)
        if cached is not None and cached[0] == ver:
            return cached[1]
        peek = self._alloc.match_prefix(req.prompt_ids, commit=False)
        req._hits_memo = (ver, peek)
        return peek

    def _admission_index(self):
        """Queue index to admit next. FIFO — except while a chunk stream
        is in flight, when the first SHORT prompt (suffix fits in one
        chunk) bypasses queued longs: a long prefill already streaming
        must not convoy every cheap prefill behind the NEXT long. Longs
        keep FIFO order among themselves, and the bypass only exists
        while a stream is active, so they cannot starve."""
        if not self.queue:
            return None
        if not (self.prefill_chunk and self._chunk_streams):
            return 0
        for i in range(len(self.queue)):
            req = self.queue.peek_at(i)
            if (req.prompt_ids.size - self._peek_hits(req).matched
                    <= self.prefill_chunk):
                return i
        return 0

    def _can_prefill(self):
        self._admit_idx = None
        if not (self.queue and self.slots.free_count):
            return False
        # cache the scan for the _prefill_step that immediately follows a
        # True answer — the anti-convoy walk match_prefix-hashes every
        # queued prompt, which is too much host work to repeat per step
        self._admit_idx = self._admission_index()
        req = self.queue.peek_at(self._admit_idx)
        peek = self._peek_hits(req)
        # reviving a cached (refcount-0) hit consumes availability just
        # like a fresh alloc; an actively shared hit is free. A mid-page
        # partial hit nets out: its COW copy costs one alloc but saves
        # one page of suffix — so `need` stays pages_for - full_hits.
        hit_pages = list(peek.pages)
        if peek.partial_page is not None:
            hit_pages.append(peek.partial_page)
        revive = sum(1 for p in hit_pages if self._alloc.refcount(p) == 0)
        need = (pages_for(req.prompt_ids.size, req.max_new_tokens,
                          self.page_size) - len(peek.pages) + revive)
        return need <= self._alloc.available - self._reserved_total

    # -- the interleaving scheduler -----------------------------------------
    def _step_action(self):
        """Chunked-prefill interleave: while a prompt is mid-stream, the
        engine alternates one chunk with one unit of other work (admit a
        waiting request or run a decode/speculation step), so queued and
        in-flight requests keep making progress underneath a long
        prefill. Decode becomes speculate-and-verify when a draft model
        is loaded."""
        if self._chunk_streams and self._chunk_turn:
            self._chunk_turn = False
            self._note_prefill_stall()
            return self._chunk_step()
        if self._can_prefill():
            self._chunk_turn = True
            self._note_prefill_stall()
            return self._prefill_step()
        if self._decodable_slots():
            self._chunk_turn = True
            if self.spec_enabled:
                return self._spec.step()
            return self._decode_step()
        if self._chunk_streams:
            return self._chunk_step()
        return {"type": "idle"}

    def _decodable_slots(self):
        active = self.slots.active_slots
        if not self._chunk_streams:
            return active
        return [s for s in active if s not in self._chunk_streams]

    # -- prefill ------------------------------------------------------------
    def _begin_paged_prefill(self, req, slot, n):
        """Match prefix hits, seat the block table, and reserve the
        request's remaining worst-case pages (prompt pages still to be
        written draw from this reservation chunk by chunk; the decode
        tail draws from it at page boundaries). Returns h — the cached
        token count the first window starts at."""
        ps = self.page_size
        hit = self._alloc.match_prefix(req.prompt_ids)   # refs hit pages
        h = hit.matched
        self._bt[slot] = list(hit.pages)
        held = len(hit.pages)
        if hit.partial_page is not None:
            # mid-page hit: the straddled page is frozen (tree-registered),
            # so take a copy-on-write split — ensure_writable swaps our ref
            # for a fresh page and the page-copy program clones the device
            # contents; the first window then overwrites [h, ...) in place
            src = hit.partial_page
            copy, _ = self._alloc.ensure_writable(src)
            self._pk, self._pv = self._copy_page(
                self._pk, self._pv, jnp.int32(src), jnp.int32(copy))
            self._bt[slot].append(copy)
            held += 1
        resv = pages_for(n, req.max_new_tokens, ps) - held
        self._resv[slot] = resv
        self._reserved_total += resv
        self.metrics.inc("prompt_tokens", n)
        self.metrics.inc("prefix_tokens_hit", h)
        self.metrics.inc("prefix_pages_hit", len(hit.pages))
        self.metrics.inc("prefix_pages_queried", (n - 1) // ps)
        return h

    def _window_prefill_device(self, req, slot, start, end, n):
        """Run one prefill window [start, end) of the prompt (the whole
        suffix, or one chunk of it) through the suffix program. Returns
        (bucket, token) — the token is meaningful only for the final
        window (end == n), which also registers the prompt's full pages
        in the prefix cache."""
        ps, Pn = self.page_size, self.pages_per_slot
        final = end == n
        # pages this window adds beyond those already seated (hits, the
        # partial-hit COW copy, earlier chunks); token-granular `start`
        # makes this ceil(end/ps) minus the seated count
        n_now = -(-end // ps) - len(self._bt[slot])
        new_pages = [self._alloc.alloc() for _ in range(n_now)]
        self._resv[slot] -= n_now
        self._reserved_total -= n_now
        self._bt[slot].extend(new_pages)
        pages = self._bt[slot]

        bt_row = np.zeros(Pn, np.int32)
        bt_row[:len(pages)] = pages
        # every page the window touches gets scattered: the straddled
        # page at start//ps (the mid-page-hit COW copy on the first
        # window, the slot's own tail page on later chunks) is rewritten
        # from the gathered stripe plus the new tokens
        touched = pages[start // ps:]
        new_vec = np.full(Pn, NULL_PAGE, np.int32)
        new_vec[:len(touched)] = touched
        sb = bucket_for(end - start, self.min_bucket, self.max_len)
        padded = np.full((1, sb), self.pad_id, np.int32)
        padded[0, :end - start] = req.prompt_ids[start:end]
        sample = final and req.temperature > 0
        with self.metrics.timer("prefill_s"):
            self._pk, self._pv, first = self._prefill_v[sample](
                self.params, jnp.asarray(padded), jnp.int32(start),
                jnp.int32(end - 1 - start), jnp.asarray(bt_row),
                jnp.asarray(new_vec), self._pk, self._pv,
                self._cos, self._sin, jnp.float32(req.temperature),
                jnp.float32(req.top_p), jnp.int32(req.top_k),
                jnp.asarray([req.seed], jnp.int32))
            first = int(first)
        if final:
            # make this prompt's FULL pages hittable right away (a
            # concurrent identical prompt shares them while this one is
            # still decoding). The partial tail page stays unregistered
            # until _retire — decode keeps writing into it, and freezing
            # it now would force an unreserved COW on the first decode
            self._alloc.register_prefix(req.prompt_ids, pages[:n // ps])
            # chunk-streamed prompts mirror into the draft window by
            # window instead (see _chunk_step) — one monolithic draft
            # prefill here would reintroduce the stall chunking removes
            if self.spec_enabled and slot not in self._chunk_streams:
                self._spec.prefill_slot(req, slot, n)
        return sb, first

    def _prefill_device(self, req, slot, n):
        """Monolithic prefill (no chunking, or suffix within one chunk)."""
        h = self._begin_paged_prefill(req, slot, n)
        return self._window_prefill_device(req, slot, h, n, n)

    def _prefill_step(self):
        """Admit the queue head; suffixes longer than `prefill_chunk`
        become a chunk STREAM advanced by later steps instead of one
        monolithic program."""
        if self.prefill_chunk is None:
            return super()._prefill_step()
        idx = self._admit_idx if self._admit_idx is not None \
            else self._admission_index()
        req = self.queue.pop_at(idx)
        slot = self._admit(req)
        n = int(req.prompt_ids.size)
        h = self._begin_paged_prefill(req, slot, n)
        if n - h <= self.prefill_chunk:
            bucket, first = self._window_prefill_device(req, slot, h, n, n)
            self.metrics.observe("chunks_per_prompt", 1)
            return self._complete_prefill(req, slot, bucket, first, n)
        self._chunk_streams[slot] = {"req": req, "n": n, "done": h,
                                     "ddone": 0, "chunks": 0,
                                     "bucket": None, "first": None}
        self.metrics.inc("chunked_prefills")
        return self._chunk_step()

    def _chunk_step(self):
        """Advance the oldest chunk stream (FIFO: the first admitted long
        prompt finishes first) by ONE bounded unit of prefill work: a
        target chunk, or — when speculation is on and the draft's mirror
        of the prompt lags the target's progress — one draft window of
        the same size, so the draft prefill never runs monolithically
        inside a single scheduler step."""
        slot = next(iter(self._chunk_streams))
        st = self._chunk_streams[slot]
        req, n = st["req"], st["n"]
        if self.spec_enabled and st["ddone"] < n and \
                (st["ddone"] < st["done"] or st["done"] == n):
            dstart = st["ddone"]
            dend = min(dstart + self.prefill_chunk, n)
            self._spec.prefill_window(req, slot, dstart, dend)
            st["ddone"] = dend
            self.metrics.inc("draft_prefill_chunks")
            if dend < n or st["done"] < n:
                return {"type": "draft_prefill_chunk",
                        "request_id": req.request_id, "slot": slot,
                        "from": dstart, "to": dend}
            return self._finish_stream(slot, st)
        start = st["done"]
        end = min(start + self.prefill_chunk, n)
        bucket, first = self._window_prefill_device(req, slot, start, end, n)
        st["done"] = end
        st["chunks"] += 1
        self.metrics.inc("prefill_chunks")
        self.metrics.inc("prefill_chunk_tokens", end - start)
        if end == n:
            st["bucket"], st["first"] = bucket, first
            # the TARGET's prompt KV is complete here; the first token is
            # only emitted at _finish_stream, which may wait whole steps
            # for the draft mirror — the prefill_done_s / ttft_s split
            self._record_prefill_done(req)
            if not (self.spec_enabled and st["ddone"] < n):
                return self._finish_stream(slot, st)
        return {"type": "prefill_chunk", "request_id": req.request_id,
                "slot": slot, "from": start, "to": end}

    def _finish_stream(self, slot, st):
        """Both the target chunks and (under speculation) the draft
        mirror are complete: retire the stream and emit the stashed
        first token."""
        del self._chunk_streams[slot]
        self.metrics.observe("chunks_per_prompt", st["chunks"])
        return self._complete_prefill(st["req"], slot, st["bucket"],
                                      st["first"], st["n"])

    # -- decode -------------------------------------------------------------
    def _ensure_tail_pages(self, slot, top):
        """Make the slot's KV positions [npos, top] writable: COW the
        current tail page if it is shared or hash-registered, then draw
        page-boundary allocations from the slot's admission-time
        reservation through `top`. The ONE home of the tail-page
        invariants — plain decode (top == npos) and the speculative
        verify window (top == min(npos + g, limit)) both call it."""
        ps = self.page_size
        pages = self._bt[slot]
        pi = int(self._npos[slot]) // ps
        if pi < len(pages):
            old = pages[pi]
            page, copied = self._alloc.ensure_writable(old)
            if copied:
                self._pk, self._pv = self._copy_page(
                    self._pk, self._pv, jnp.int32(old), jnp.int32(page))
                pages[pi] = page
        while len(pages) * ps <= top:
            pages.append(self._alloc.alloc())
            self._resv[slot] -= 1
            self._reserved_total -= 1

    def _decode_device(self, active):
        Pn = self.pages_per_slot
        for slot in active:
            self._ensure_tail_pages(slot, int(self._npos[slot]))
        bt = np.full((self.max_slots, Pn), NULL_PAGE, np.int32)
        for slot in active:
            bt[slot, :len(self._bt[slot])] = self._bt[slot]
        with self.metrics.timer("decode_step_s"):
            self._pk, self._pv, nxt = self._decode_v[
                self._sampling_active()](
                self.params, jnp.asarray(self._last_tok), self._pk,
                self._pv, jnp.asarray(bt), jnp.asarray(self._npos),
                self._cos, self._sin, *self._sampling_args())
        return np.asarray(nxt)

    # -- lifecycle ----------------------------------------------------------
    def _retire(self, slot):
        # the slot stops writing here, so its partial PROMPT tail page is
        # finally frozen: hang it on the radix tree (full pages were
        # registered at prefill; this extends the cached prefix to token
        # granularity — contents beyond the prompt are decode K/V that
        # partial_len keeps unreachable). Only prompt positions are
        # cached: their bytes came from prefill programs, so later hits
        # replay the exact values a fresh prefill would compute.
        req = self.slots.owner(slot)
        if req is not None and int(self._npos[slot]) >= req.prompt_ids.size:
            n = int(req.prompt_ids.size)
            n_pages = -(-n // self.page_size)
            self._alloc.register_prefix(req.prompt_ids,
                                        self._bt[slot][:n_pages])
        for p in self._bt[slot]:
            self._alloc.release(p)
        self._bt[slot] = []
        self._reserved_total -= self._resv.pop(slot, 0)
        if self.spec_enabled:
            self._spec.retire(slot)
        super()._retire(slot)

    # -- preemption ---------------------------------------------------------
    def preempt(self, slot):
        """Evict a DECODING request from its slot without losing work:
        the returned state is the block table (page ids, refcounts still
        held — the allocator cannot hand the pages out or evict them,
        and prefix hits against the prompt's registered pages stay
        COW-safe), the KV write position, and the last token. `resume`
        re-seats it and the continuation is bit-identical to never
        having been preempted: decode depends only on the held pages'
        contents, the block table, `npos`, the last token, and the
        (seed, pos) sampling stream — all preserved. The slot's
        remaining page reservation is refunded while preempted, which is
        the point: a waiting request can use it."""
        req = self.slots.owner(slot)
        if slot in self._chunk_streams:
            raise ValueError(f"slot {slot} is mid-prefill-stream; only "
                             "decoding slots are preemptible")
        if self.spec_enabled:
            raise ValueError("preemption with speculative decoding is "
                             "unsupported (the draft's stripe cache is "
                             "not checkpointed)")
        state = {"req": req, "pages": self._bt[slot],
                 "npos": int(self._npos[slot]),
                 "last_tok": int(self._last_tok[slot]),
                 "resv": self._resv.get(slot, 0)}
        self._bt[slot] = []
        self._reserved_total -= self._resv.pop(slot, 0)
        self.slots.retire(slot)
        self._npos[slot] = 0
        self._last_tok[slot] = self.pad_id
        self.sampler.clear(slot)
        self.metrics.inc("preemptions")
        return state

    def can_resume(self, state):
        return bool(self.slots.free_count) and \
            state["resv"] <= self._alloc.available - self._reserved_total

    def resume(self, state):
        """Re-seat a preempted request (see `preempt`); returns its new
        slot. Caller must have checked `can_resume`."""
        req = state["req"]
        slot = self._admit(req)
        self._bt[slot] = state["pages"]
        self._resv[slot] = state["resv"]
        self._reserved_total += state["resv"]
        self._npos[slot] = state["npos"]
        self._last_tok[slot] = state["last_tok"]
        self.metrics.inc("resumes")
        return slot

    def reset(self):
        """Forget all requests, block tables, AND the prefix cache (cold
        cache — a warm timed run after reset would be all hits and lie);
        compiled programs and compile counters survive."""
        super().reset()
        # the page pool survives a reset, so its byte gauge must too
        self.metrics.set_gauge("kv_pool_bytes", 2 * sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(self._pk)))
        self._alloc = BlockAllocator(self.num_pages, self.page_size,
                                     metrics=self.metrics,
                                     policy=self.prefix_policy)
        self._bt = [[] for _ in range(self.max_slots)]
        self._resv = {}
        self._reserved_total = 0
        self._chunk_streams = {}
        self._chunk_turn = False
        if self.spec_enabled:
            self._spec.reset()
