"""Draft-model speculative decoding over the paged serving engine.

Leviathan-style greedy speculation (Leviathan et al. 2023), organized so
the whole round is two device dispatches regardless of the draft length:

  PROPOSE — a cheap draft model (e.g. `generation.draft_from_params`
  truncation) runs `spec_tokens` greedy decode steps over its OWN stripe
  cache in ONE traced scan. Step j of row r feeds the committed tokens
  the draft hasn't ingested yet (forced-feed catch-up — after a fully
  accepted round the draft is one token behind the target) and its own
  previous output after that.

  VERIFY — the target model scores the whole window [last committed
  token, draft_1..draft_g] in ONE batched paged forward
  (`generation._paged_forward_verify`): token i of row r at position
  pos[r]+i, K/V scattered into the row's tail pages write-before-attend,
  writes past the row's page reservation redirected to the null page.

  ACCEPT — the host commits the longest exactly-matching prefix plus the
  target's own next token: between 1 and g+1 tokens per round, every one
  of them exactly the target's greedy sequence (speculation changes the
  schedule, never the output).

  ROLL BACK — rejected tail tokens are erased by truncating the
  watermark (`_npos`) and the BLOCK TABLE: tail pages allocated for the
  window that end up wholly past the new watermark are released back to
  the pool and their reservation refunded, so after a worst-case
  all-rejected round the block table and page refcounts are bit-identical
  to a plain decode step's (tested). The partially-filled tail page keeps
  its rejected K/V as garbage — the write-before-attend order overwrites
  it before the position mask ever exposes it. Shared/registered tail
  pages are COW'd before the window writes, exactly as plain decode.

The draft stays REPLICATED under a tensor-parallel mesh (its whole point
is being cheap); only the target-side verify shards.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from paddle_tpu.models import generation as gen
from paddle_tpu.models import llama_functional as lf
from paddle_tpu.serving.block_manager import NULL_PAGE
from paddle_tpu.serving.scheduler import bucket_for

__all__ = ["SpecDecoder"]


def _paged_verify_traced(params, ids, pk, pv, bt, pos, limit, cos, sin, *,
                         args, metrics, page_size, tp_axis=None,
                         tp_degree=1):
    """Target-model half of a speculation round: score the whole draft
    window [b, g+1] in one forward (token i of row r at position
    pos[r]+i), writing its K/V into the tail pages (positions past
    limit[r] go to the null page). Returns the target's greedy token at
    every window position — the host accepts the longest exact match."""
    metrics.inc("verify_compiles")
    logits, pk, pv = gen._paged_forward_verify(
        params, ids, pk, pv, bt, pos, limit, cos, sin, args, page_size,
        tp_axis=tp_axis, tp_degree=tp_degree)
    return pk, pv, jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _draft_window_traced(params, ids, h, ck, cv, slot, cos, sin, *, args,
                         metrics):
    """One prefill WINDOW of the draft's stripe cache: forward ids
    [1, sb] at traced offset h, writing KV slots [h, h+sb) of `slot`'s
    stripe (earlier windows' KV below h is already in place — the same
    suffix-at-a-deeper-h trick the target's chunked prefill uses, minus
    the prefix cache: the draft has none, so its windows start at 0).
    Logits are discarded — the draft only needs the KV."""
    metrics.inc("draft_prefill_compiles")
    sb = ids.shape[1]
    max_len = ck.shape[3]
    sck = jax.lax.dynamic_slice_in_dim(ck, slot, 1, axis=1)
    scv = jax.lax.dynamic_slice_in_dim(cv, slot, 1, axis=1)
    # pad the scratch stripe by the bucket so the write at [h, h+sb) can
    # never clamp (the overshoot trick the target's suffix prefill uses);
    # the pad tail is sliced off before writing back
    pad = jnp.zeros(sck.shape[:3] + (sb,) + sck.shape[4:], sck.dtype)
    tk = jnp.concatenate([sck, pad], axis=3)
    tv = jnp.concatenate([scv, pad], axis=3)
    _, tk, tv = gen._forward_cached(params, ids, tk, tv, h, cos, sin,
                                    args, last_idx=0)
    ck = jax.lax.dynamic_update_slice_in_dim(
        ck, jax.lax.slice_in_dim(tk, 0, max_len, axis=3), slot, axis=1)
    cv = jax.lax.dynamic_update_slice_in_dim(
        cv, jax.lax.slice_in_dim(tv, 0, max_len, axis=3), slot, axis=1)
    return ck, cv


def _draft_propose_traced(params, forced, n_forced, start, ck, cv, cos,
                          sin, *, args, metrics, steps):
    """Draft-model propose: `steps` greedy decode steps over the draft's
    stripe cache in ONE traced scan (one device dispatch per round, not
    per token). Step j of row r feeds forced[r, j] while j < n_forced[r]
    — the committed tokens the draft hasn't ingested yet (its own last
    token, plus one catch-up token after a fully-accepted round) — and
    its own previous output after that, at position start[r] + j."""
    metrics.inc("draft_propose_compiles")

    def stepf(carry, xs):
        prev, ck, cv = carry
        j, forced_j = xs
        tok = jnp.where(j < n_forced, forced_j, prev)
        logits, ck, cv = gen._forward_cached(
            params, tok[:, None], ck, cv, start + j, cos, sin, args)
        out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (out, ck, cv), out

    (_, ck, cv), outs = jax.lax.scan(
        stepf, (forced[:, 0], ck, cv),
        (jnp.arange(steps, dtype=jnp.int32), jnp.swapaxes(forced, 0, 1)))
    return ck, cv, jnp.swapaxes(outs, 0, 1)    # [S, steps]


class SpecDecoder:
    """The speculative half of a `PagedEngine`: owns the draft model's
    stripe cache + programs and the target's sharded verify program, and
    runs the propose → verify → accept → roll-back round. Mutates the
    engine's block tables / positions / reservations in place — it IS
    the engine's decode step while a draft model is loaded."""

    def __init__(self, engine, donate):
        from paddle_tpu.serving.engine import _prefill_traced

        self.eng = engine
        self.g = engine.spec_tokens
        dargs = engine.draft_args
        self.draft_params = engine.draft_params
        self.draft_args = dargs
        Ld = lf.stack_leading_dim(self.draft_params["layers"])
        dhd = dargs.hidden_size // dargs.num_heads
        ddtype = self.draft_params["embedding"].dtype
        self._dck = jnp.zeros(
            (Ld, engine.max_slots, dargs.num_kv_heads, engine.max_len,
             dhd), ddtype)
        self._dcv = jnp.zeros_like(self._dck)
        # 2*max_len tables: window prefills forward a bucket at offset h,
        # and h+bucket can overshoot max_len before masking trims it (the
        # same overshoot the target's suffix prefill pads for)
        self._dcos, self._dsin = lf.rope_tables(2 * engine.max_len, dhd,
                                                dargs.rope_theta)
        self._dpos = np.zeros(engine.max_slots, np.int32)
        self._draft_prefill = jax.jit(
            functools.partial(_prefill_traced, args=dargs,
                              metrics=engine.metrics,
                              counter="draft_prefill_compiles"),
            donate_argnums=(3, 4) if donate else (),
            static_argnames=("sample",))
        self._draft_window = jax.jit(
            functools.partial(_draft_window_traced, args=dargs,
                              metrics=engine.metrics),
            donate_argnums=(3, 4) if donate else ())
        # g+1 draft steps, not g: after a fully-accepted round the draft
        # is one token behind the target (lag 1), and the extra step keeps
        # every verify column backed by a FRESH proposal — lag then
        # stabilizes at <= 1 instead of climbing on repetitive text while
        # clamped duplicate drafts keep matching
        self._draft_propose = jax.jit(
            functools.partial(_draft_propose_traced, args=dargs,
                              metrics=engine.metrics, steps=self.g + 1),
            donate_argnums=(4, 5) if donate else ())
        rep = P()
        self._verify = engine._sharded(
            functools.partial(
                _paged_verify_traced, args=engine.args,
                metrics=engine.metrics, page_size=engine.page_size,
                tp_axis=engine.tp_axis if engine.mesh is not None else None,
                tp_degree=engine.tp_degree),
            in_specs=(engine._pspecs, rep, engine._poolspec,
                      engine._poolspec, rep, rep, rep, rep, rep),
            out_specs=(engine._poolspec, engine._poolspec, rep),
            donate=(2, 3) if donate else ())

    # -- lifecycle -----------------------------------------------------------
    def prefill_slot(self, req, slot, n):
        """Mirror the finished prompt into the draft's stripe cache."""
        eng = self.eng
        bucket = bucket_for(n, eng.min_bucket, eng.max_len)
        padded = np.full((1, bucket), eng.pad_id, np.int32)
        padded[0, :n] = req.prompt_ids
        with eng.metrics.timer("draft_prefill_s"):
            self._dck, self._dcv, _ = self._draft_prefill(
                self.draft_params, jnp.asarray(padded), jnp.int32(n),
                self._dck, self._dcv, jnp.int32(slot), self._dcos,
                self._dsin, jnp.float32(0.0), jnp.float32(1.0),
                jnp.int32(0), jnp.asarray([0], jnp.int32), sample=False)
        self._dpos[slot] = n

    def prefill_window(self, req, slot, start, end):
        """Advance the draft's mirror of a chunk-streamed prompt by one
        window [start, end) — the draft prefill rides the same bounded
        scheduler steps as the target's chunks instead of running the
        whole prompt monolithically at the final chunk (which would
        reintroduce exactly the stall chunking removes). Windows start
        at 0: the draft has no prefix cache."""
        eng = self.eng
        n = int(req.prompt_ids.size)
        sb = bucket_for(end - start, eng.min_bucket, eng.max_len)
        padded = np.full((1, sb), eng.pad_id, np.int32)
        padded[0, :end - start] = req.prompt_ids[start:end]
        with eng.metrics.timer("draft_prefill_s"):
            self._dck, self._dcv = self._draft_window(
                self.draft_params, jnp.asarray(padded), jnp.int32(start),
                self._dck, self._dcv, jnp.int32(slot), self._dcos,
                self._dsin)
        # track the mirror frontier as windows land (not just at end == n):
        # speculation rounds for OTHER slots run the propose scan over all
        # S rows, and a row's scan writes land at _dpos[row] — pointing a
        # mid-stream row's writes at its frontier keeps them on positions
        # the next window rewrites anyway, instead of clobbering the
        # already-mirrored prefix at 0
        self._dpos[slot] = end

    def retire(self, slot):
        self._dpos[slot] = 0

    def reset(self):
        self._dpos[:] = 0

    # -- the round -----------------------------------------------------------
    def _seq_token(self, req, idx):
        """Committed token at sequence index idx (prompt, then outputs)."""
        n = req.prompt_ids.size
        return int(req.prompt_ids[idx]) if idx < n \
            else int(req.token_ids[idx - n])

    def _limit(self, slot):
        """A row's last legal KV write index — the top of its
        admission-time page reservation (`scheduler.pages_for`)."""
        req = self.eng.slots.owner(slot)
        return int(req.prompt_ids.size) + req.max_new_tokens - 2

    def _propose_device(self, forced, n_forced, start):
        """One draft-scan dispatch (separate method so tests can stub an
        adversarial draft)."""
        with self.eng.metrics.timer("draft_propose_s"):
            self._dck, self._dcv, outs = self._draft_propose(
                self.draft_params, jnp.asarray(forced),
                jnp.asarray(n_forced), jnp.asarray(start), self._dck,
                self._dcv, self._dcos, self._dsin)
        return np.asarray(outs)                           # [S, g]

    def step(self):
        """One speculation round: draft proposes g tokens (one traced
        scan), the target verifies the whole window (one batched paged
        forward), the host commits the longest exactly-matching prefix
        plus the target's next token — between 1 and g+1 tokens per
        round, all of them exactly the target's greedy sequence — then
        rolls the block table back to the new watermark."""
        eng = self.eng
        active = eng._decodable_slots()
        S, g = eng.max_slots, self.g
        steps = g + 1
        Pn = eng.pages_per_slot

        # ---- propose -----------------------------------------------------
        # the scan runs over ALL S rows; non-active rows (free, or a
        # prompt mid-chunked-prefill) still get pad-fed writes at
        # start[r] + j, so start MUST be each row's own frontier (_dpos):
        # writes then hit positions later windows / decode steps rewrite,
        # never the valid mirrored prefix below the frontier
        forced = np.zeros((S, steps), np.int32)
        n_forced = np.ones(S, np.int32)
        start = np.asarray(self._dpos, np.int32).copy()
        lag = {}
        for slot in active:
            req = eng.slots.owner(slot)
            lag[slot] = int(eng._npos[slot]) - int(self._dpos[slot])
            start[slot] = self._dpos[slot]
            n_forced[slot] = lag[slot] + 1
            for j in range(min(lag[slot] + 1, steps)):
                forced[slot, j] = self._seq_token(
                    req, int(self._dpos[slot]) + j)
        outs = self._propose_device(forced, n_forced, start)

        # ---- tail pages for the verify window ----------------------------
        limit = np.full(S, -1, np.int32)
        for slot in active:
            limit[slot] = self._limit(slot)
            eng._ensure_tail_pages(
                slot, min(int(eng._npos[slot]) + g, int(limit[slot])))

        # ---- verify ------------------------------------------------------
        ids = np.full((S, g + 1), eng.pad_id, np.int32)
        for slot in active:
            ids[slot, 0] = eng._last_tok[slot]
            for i in range(1, g + 1):
                j = lag[slot] + i - 1            # draft for index npos+i
                # lag <= 1 keeps j within the proposals (defensive clamp
                # against an adversarial/stubbed shorter propose)
                ids[slot, i] = outs[slot, min(j, outs.shape[1] - 1)]
        bt = np.full((S, Pn), NULL_PAGE, np.int32)
        for slot in active:
            bt[slot, :len(eng._bt[slot])] = eng._bt[slot]
        with eng.metrics.timer("verify_s"):
            eng._pk, eng._pv, tgt = self._verify(
                eng.params, jnp.asarray(ids), eng._pk, eng._pv,
                jnp.asarray(bt), jnp.asarray(eng._npos),
                jnp.asarray(limit), eng._cos, eng._sin)
            tgt = np.asarray(tgt)                         # [S, g+1]

        # ---- accept + roll back ------------------------------------------
        emitted = {}
        for slot in active:
            req = eng.slots.owner(slot)
            p = int(eng._npos[slot])
            drafts = [int(ids[slot, i]) for i in range(1, g + 1)]
            a = 0
            while a < g and drafts[a] == int(tgt[slot, a]):
                a += 1
            commit = drafts[:a] + [int(tgt[slot, a])] if a < g \
                else drafts + [int(tgt[slot, g])]
            k = 0
            for tok in commit:
                eng._emit(req, tok)
                k += 1
                if req.finished:
                    break
            eng._npos[slot] = p + k
            eng._last_tok[slot] = req.token_ids[-1]
            self._dpos[slot] = min(int(start[slot]) + steps,
                                   p + min(a, k) + 1, p + k)
            emitted[req.request_id] = commit[:k]
            eng.metrics.inc("draft_tokens_proposed", g)
            eng.metrics.inc("draft_tokens_accepted", min(a, k))
            eng.metrics.inc("tokens_generated", k)
            eng.metrics.observe("spec_commit_len", k)
            eng.metrics.observe("spec_acceptance_rate", min(a, k) / g)
            if req.finished:
                eng._retire(slot)
            else:
                self._rollback_tail(slot, p + k)
        eng.metrics.inc("spec_rounds")
        eng.metrics.observe("tokens_per_decode_step",
                            sum(len(v) for v in emitted.values()))
        return {"type": "spec_decode", "tokens": emitted}

    def _rollback_tail(self, slot, npos):
        """Truncate the slot's block table to the pages covering the
        committed positions [0, npos): window pages wholly past the new
        watermark return to the pool and their reservation is refunded.
        The rejected K/V inside the kept tail page stays as garbage that
        the next write-before-attend step overwrites."""
        eng = self.eng
        keep = (npos - 1) // eng.page_size + 1
        pages = eng._bt[slot]
        while len(pages) > keep:
            eng._alloc.release(pages.pop())
            eng._resv[slot] += 1
            eng._reserved_total += 1
            eng.metrics.inc("spec_pages_rewound")
