"""Draft-model speculative decoding over the paged serving engine.

Leviathan-style greedy speculation (Leviathan et al. 2023), organized so
the whole round is two device dispatches regardless of the draft length:

  PROPOSE — a cheap draft model (e.g. `generation.draft_from_params`
  truncation) runs `spec_tokens` greedy decode steps over its OWN stripe
  cache in ONE traced scan. Step j of row r feeds the committed tokens
  the draft hasn't ingested yet (forced-feed catch-up — after a fully
  accepted round the draft is one token behind the target) and its own
  previous output after that.

  VERIFY — the target model scores the whole window [last committed
  token, draft_1..draft_g] in ONE batched paged forward
  (`generation._paged_forward_verify`): token i of row r at position
  pos[r]+i, K/V scattered into the row's tail pages write-before-attend,
  writes past the row's page reservation redirected to the null page.

  ACCEPT — greedy rows commit the longest exactly-matching prefix plus
  the target's own next token: between 1 and g+1 tokens per round, every
  one of them exactly the target's greedy sequence (speculation changes
  the schedule, never the output). Sampling rows use Leviathan rejection
  sampling instead: draft token i is accepted with probability
  min(1, p_target(d)/p_draft(d)); the first rejection commits ONE token
  resampled from the adjusted residual normalize(max(0, p_t - p_d)), a
  fully accepted window commits a bonus token from the target's next
  distribution through the sequential per-request (seed, pos) gumbel
  stream. Every committed token is exactly target-distributed, and when
  draft == target the ratio is 1 so the output is token-for-token the
  sequential seeded sample (parity-tested).

  ROLL BACK — rejected tail tokens are erased by truncating the
  watermark (`_npos`) and the BLOCK TABLE: tail pages allocated for the
  window that end up wholly past the new watermark are released back to
  the pool and their reservation refunded, so after a worst-case
  all-rejected round the block table and page refcounts are bit-identical
  to a plain decode step's (tested). The partially-filled tail page keeps
  its rejected K/V as garbage — the write-before-attend order overwrites
  it before the position mask ever exposes it. Shared/registered tail
  pages are COW'd before the window writes, exactly as plain decode.

The draft stays REPLICATED under a tensor-parallel mesh (its whole point
is being cheap); only the target-side verify shards.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from paddle_tpu.models import generation as gen
from paddle_tpu.models import llama_functional as lf
from paddle_tpu.serving.block_manager import NULL_PAGE
from paddle_tpu.serving.scheduler import bucket_for

__all__ = ["SpecDecoder"]


def _paged_verify_traced(params, ids, pk, pv, bt, pos, limit, cos, sin, *,
                         args, metrics, page_size, tp_axis=None,
                         tp_degree=1):
    """Target-model half of a speculation round: score the whole draft
    window [b, g+1] in one forward (token i of row r at position
    pos[r]+i), writing its K/V into the tail pages (positions past
    limit[r] go to the null page). Returns the target's greedy token at
    every window position — the host accepts the longest exact match."""
    metrics.inc("verify_compiles")
    logits, pk, pv = gen._paged_forward_verify(
        params, ids, pk, pv, bt, pos, limit, cos, sin, args, page_size,
        tp_axis=tp_axis, tp_degree=tp_degree)
    return pk, pv, jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _draft_window_traced(params, ids, h, ck, cv, slot, cos, sin, *, args,
                         metrics):
    """One prefill WINDOW of the draft's stripe cache: forward ids
    [1, sb] at traced offset h, writing KV slots [h, h+sb) of `slot`'s
    stripe (earlier windows' KV below h is already in place — the same
    suffix-at-a-deeper-h trick the target's chunked prefill uses, minus
    the prefix cache: the draft has none, so its windows start at 0).
    Logits are discarded — the draft only needs the KV."""
    metrics.inc("draft_prefill_compiles")
    sb = ids.shape[1]
    max_len = ck.shape[3]
    sck = jax.lax.dynamic_slice_in_dim(ck, slot, 1, axis=1)
    scv = jax.lax.dynamic_slice_in_dim(cv, slot, 1, axis=1)
    # pad the scratch stripe by the bucket so the write at [h, h+sb) can
    # never clamp (the overshoot trick the target's suffix prefill uses);
    # the pad tail is sliced off before writing back
    pad = jnp.zeros(sck.shape[:3] + (sb,) + sck.shape[4:], sck.dtype)
    tk = jnp.concatenate([sck, pad], axis=3)
    tv = jnp.concatenate([scv, pad], axis=3)
    _, tk, tv = gen._forward_cached(params, ids, tk, tv, h, cos, sin,
                                    args, last_idx=0)
    ck = jax.lax.dynamic_update_slice_in_dim(
        ck, jax.lax.slice_in_dim(tk, 0, max_len, axis=3), slot, axis=1)
    cv = jax.lax.dynamic_update_slice_in_dim(
        cv, jax.lax.slice_in_dim(tv, 0, max_len, axis=3), slot, axis=1)
    return ck, cv


def _paged_verify_sampled_traced(params, ids, pk, pv, bt, pos, limit, cos,
                                 sin, temp, top_p, top_k, *, args, metrics,
                                 page_size, tp_axis=None, tp_degree=1):
    """Verify variant for rejection-sampling rounds: same paged window
    forward, but alongside the greedy argmax it returns the target's
    WARPED distribution at every window position (softmax over the
    shared `_warp_logits` masking) — the p_target the host acceptance
    test and residual resample consume. Greedy rounds keep the slimmer
    `_paged_verify_traced` program (and its captured golden)."""
    metrics.inc("verify_compiles")
    logits, pk, pv = gen._paged_forward_verify(
        params, ids, pk, pv, bt, pos, limit, cos, sin, args, page_size,
        tp_axis=tp_axis, tp_degree=tp_degree)
    S, W, V = logits.shape
    masked, _ = gen._warp_logits(logits.reshape(S * W, V),
                                 jnp.repeat(temp, W), jnp.repeat(top_p, W),
                                 jnp.repeat(top_k, W))
    probs = jax.nn.softmax(masked, axis=-1).reshape(S, W, V)
    return (pk, pv, jnp.argmax(logits, axis=-1).astype(jnp.int32), probs)


def _draft_propose_traced(params, forced, n_forced, start, ck, cv, cos,
                          sin, temp, top_p, top_k, seeds, *, args, metrics,
                          steps, sample=False):
    """Draft-model propose: `steps` decode steps over the draft's stripe
    cache in ONE traced scan (one device dispatch per round, not per
    token). Step j of row r feeds forced[r, j] while j < n_forced[r] —
    the committed tokens the draft hasn't ingested yet (its own last
    token, plus one catch-up token after a fully-accepted round) — and
    its own previous output after that, at position start[r] + j.

    sample=False (greedy rounds) proposes by argmax. sample=True draws
    step j's token from the draft's WARPED distribution via the
    request's own (seed, position) gumbel stream — the `_row_keys`
    stream sequential `generate(seeds=...)` uses, at the proposed
    token's sequence index start + j + 1 — and additionally returns
    those warped distributions [S, steps, vocab]: the p_draft of the
    host's accept-with-prob-min(1, p_target/p_draft) test. Greedy rows
    (temperature <= 0) inside a mixed batch still propose exact argmax
    (`_sample`'s greedy_rows path)."""
    metrics.inc("draft_propose_compiles")

    def stepf(carry, xs):
        prev, ck, cv = carry
        j, forced_j = xs
        tok = jnp.where(j < n_forced, forced_j, prev)
        logits, ck, cv = gen._forward_cached(
            params, tok[:, None], ck, cv, start + j, cos, sin, args)
        if sample:
            out = gen._sample(logits, True, temp, top_p, None, top_k,
                              row_keys=gen._row_keys(seeds, start + j + 1))
            masked, _ = gen._warp_logits(logits, temp, top_p, top_k)
            probs = jax.nn.softmax(masked, axis=-1)
        else:
            out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            probs = jnp.zeros((), jnp.float32)
        return (out, ck, cv), (out, probs)

    (_, ck, cv), (outs, probs) = jax.lax.scan(
        stepf, (forced[:, 0], ck, cv),
        (jnp.arange(steps, dtype=jnp.int32), jnp.swapaxes(forced, 0, 1)))
    outs = jnp.swapaxes(outs, 0, 1)            # [S, steps]
    if sample:
        return ck, cv, outs, jnp.swapaxes(probs, 0, 1)  # +[S, steps, V]
    return ck, cv, outs


_ACCEPT_SALT = 0xAC          # acceptance-test uniform branch
_RESAMPLE_SALT = 0x5E        # residual-resample gumbel branch


def _spec_key(seed, pos, salt):
    """Host-side PRNG key for one (request, position) decision in a
    rejection-sampling round: a salted branch of the request's
    `_row_keys` (seed, position) stream — deterministic across
    schedules (batch composition, chunking, preemption never change
    it), and independent of the gumbel draws that CHOSE the draft
    token (reusing those would correlate the accept test with the
    proposal and bias the output distribution)."""
    k = jax.random.fold_in(jax.random.key(0), seed)
    k = jax.random.fold_in(k, pos)
    return jax.random.fold_in(k, salt)


def _residual_draw(residual, seed, pos):
    """Sample from the normalized residual max(0, p_t - p_d)/Z via
    gumbel-max on its log — the adjusted distribution that makes the
    round's committed token EXACTLY target-distributed (Leviathan et
    al. 2023, Thm. 1) regardless of draft quality."""
    gumbel = np.asarray(jax.random.gumbel(
        _spec_key(seed, pos, _RESAMPLE_SALT), residual.shape))
    logp = np.where(residual > 0, np.log(np.maximum(residual, 1e-30)),
                    -np.inf)
    return int(np.argmax(logp + gumbel))


class SpecDecoder:
    """The speculative half of a `PagedEngine`: owns the draft model's
    stripe cache + programs and the target's sharded verify program, and
    runs the propose → verify → accept → roll-back round. Mutates the
    engine's block tables / positions / reservations in place — it IS
    the engine's decode step while a draft model is loaded."""

    def __init__(self, engine, donate):
        from paddle_tpu.serving.engine import _prefill_traced

        self.eng = engine
        self.g = engine.spec_tokens
        dargs = engine.draft_args
        self.draft_params = engine.draft_params
        self.draft_args = dargs
        Ld = lf.stack_leading_dim(self.draft_params["layers"])
        dhd = dargs.hidden_size // dargs.num_heads
        ddtype = self.draft_params["embedding"].dtype
        self._dck = jnp.zeros(
            (Ld, engine.max_slots, dargs.num_kv_heads, engine.max_len,
             dhd), ddtype)
        self._dcv = jnp.zeros_like(self._dck)
        # 2*max_len tables: window prefills forward a bucket at offset h,
        # and h+bucket can overshoot max_len before masking trims it (the
        # same overshoot the target's suffix prefill pads for)
        self._dcos, self._dsin = lf.rope_tables(2 * engine.max_len, dhd,
                                                dargs.rope_theta)
        self._dpos = np.zeros(engine.max_slots, np.int32)
        self._draft_prefill = jax.jit(
            functools.partial(_prefill_traced, args=dargs,
                              metrics=engine.metrics,
                              counter="draft_prefill_compiles"),
            donate_argnums=(3, 4) if donate else (),
            static_argnames=("sample",))
        self._draft_window = jax.jit(
            functools.partial(_draft_window_traced, args=dargs,
                              metrics=engine.metrics),
            donate_argnums=(3, 4) if donate else ())
        # g+1 draft steps, not g: after a fully-accepted round the draft
        # is one token behind the target (lag 1), and the extra step keeps
        # every verify column backed by a FRESH proposal — lag then
        # stabilizes at <= 1 instead of climbing on repetitive text while
        # clamped duplicate drafts keep matching
        self._draft_propose = jax.jit(
            functools.partial(_draft_propose_traced, args=dargs,
                              metrics=engine.metrics, steps=self.g + 1),
            donate_argnums=(4, 5) if donate else (),
            static_argnames=("sample",))
        rep = P()
        tp_kw = dict(
            args=engine.args, metrics=engine.metrics,
            page_size=engine.page_size,
            tp_axis=engine.tp_axis if engine.mesh is not None else None,
            tp_degree=engine.tp_degree)
        self._verify = engine._sharded(
            functools.partial(_paged_verify_traced, **tp_kw),
            in_specs=(engine._pspecs, rep, engine._poolspec,
                      engine._poolspec, rep, rep, rep, rep, rep),
            out_specs=(engine._poolspec, engine._poolspec, rep),
            donate=(2, 3) if donate else ())
        # the rejection-sampling verify also returns the warped target
        # distributions; built lazily-adjacent here so greedy-only
        # engines never trace it
        self._verify_sampled = engine._sharded(
            functools.partial(_paged_verify_sampled_traced, **tp_kw),
            in_specs=(engine._pspecs, rep, engine._poolspec,
                      engine._poolspec, rep, rep, rep, rep, rep, rep,
                      rep, rep),
            out_specs=(engine._poolspec, engine._poolspec, rep, rep),
            donate=(2, 3) if donate else ())

    # -- lifecycle -----------------------------------------------------------
    def prefill_slot(self, req, slot, n):
        """Mirror the finished prompt into the draft's stripe cache."""
        eng = self.eng
        bucket = bucket_for(n, eng.min_bucket, eng.max_len)
        padded = np.full((1, bucket), eng.pad_id, np.int32)
        padded[0, :n] = req.prompt_ids
        with eng.metrics.timer("draft_prefill_s"):
            self._dck, self._dcv, _ = self._draft_prefill(
                self.draft_params, jnp.asarray(padded), jnp.int32(n),
                self._dck, self._dcv, jnp.int32(slot), self._dcos,
                self._dsin, jnp.float32(0.0), jnp.float32(1.0),
                jnp.int32(0), jnp.asarray([0], jnp.int32), sample=False)
        self._dpos[slot] = n

    def prefill_window(self, req, slot, start, end):
        """Advance the draft's mirror of a chunk-streamed prompt by one
        window [start, end) — the draft prefill rides the same bounded
        scheduler steps as the target's chunks instead of running the
        whole prompt monolithically at the final chunk (which would
        reintroduce exactly the stall chunking removes). Windows start
        at 0: the draft has no prefix cache."""
        eng = self.eng
        n = int(req.prompt_ids.size)
        sb = bucket_for(end - start, eng.min_bucket, eng.max_len)
        padded = np.full((1, sb), eng.pad_id, np.int32)
        padded[0, :end - start] = req.prompt_ids[start:end]
        with eng.metrics.timer("draft_prefill_s"):
            self._dck, self._dcv = self._draft_window(
                self.draft_params, jnp.asarray(padded), jnp.int32(start),
                self._dck, self._dcv, jnp.int32(slot), self._dcos,
                self._dsin)
        # track the mirror frontier as windows land (not just at end == n):
        # speculation rounds for OTHER slots run the propose scan over all
        # S rows, and a row's scan writes land at _dpos[row] — pointing a
        # mid-stream row's writes at its frontier keeps them on positions
        # the next window rewrites anyway, instead of clobbering the
        # already-mirrored prefix at 0
        self._dpos[slot] = end

    def retire(self, slot):
        self._dpos[slot] = 0

    def reset(self):
        self._dpos[:] = 0

    # -- the round -----------------------------------------------------------
    def _seq_token(self, req, idx):
        """Committed token at sequence index idx (prompt, then outputs)."""
        n = req.prompt_ids.size
        return int(req.prompt_ids[idx]) if idx < n \
            else int(req.token_ids[idx - n])

    def _limit(self, slot):
        """A row's last legal KV write index — the top of its
        admission-time page reservation (`scheduler.pages_for`)."""
        req = self.eng.slots.owner(slot)
        return int(req.prompt_ids.size) + req.max_new_tokens - 2

    def _propose_device(self, forced, n_forced, start, sample=False):
        """One draft-scan dispatch (separate method so tests can stub an
        adversarial draft). Returns (outs, probs) — probs is None on
        greedy rounds."""
        eng = self.eng
        with eng.metrics.timer("draft_propose_s"):
            out = self._draft_propose(
                self.draft_params, jnp.asarray(forced),
                jnp.asarray(n_forced), jnp.asarray(start), self._dck,
                self._dcv, self._dcos, self._dsin,
                *eng.sampler.device_args(), sample=sample)
            if sample:
                self._dck, self._dcv, outs, probs = out
                return np.asarray(outs), np.asarray(probs)
            self._dck, self._dcv, outs = out
        return np.asarray(outs), None                     # [S, steps]

    def step(self):
        """One speculation round: draft proposes g tokens (one traced
        scan), the target verifies the whole window (one batched paged
        forward), the host commits the longest exactly-matching prefix
        plus the target's next token — between 1 and g+1 tokens per
        round, all of them exactly the target's greedy sequence — then
        rolls the block table back to the new watermark."""
        eng = self.eng
        active = eng._decodable_slots()
        S, g = eng.max_slots, self.g
        steps = g + 1
        Pn = eng.pages_per_slot

        # ---- propose -----------------------------------------------------
        # the scan runs over ALL S rows; non-active rows (free, or a
        # prompt mid-chunked-prefill) still get pad-fed writes at
        # start[r] + j, so start MUST be each row's own frontier (_dpos):
        # writes then hit positions later windows / decode steps rewrite,
        # never the valid mirrored prefix below the frontier
        forced = np.zeros((S, steps), np.int32)
        n_forced = np.ones(S, np.int32)
        start = np.asarray(self._dpos, np.int32).copy()
        lag = {}
        for slot in active:
            req = eng.slots.owner(slot)
            lag[slot] = int(eng._npos[slot]) - int(self._dpos[slot])
            start[slot] = self._dpos[slot]
            n_forced[slot] = lag[slot] + 1
            for j in range(min(lag[slot] + 1, steps)):
                forced[slot, j] = self._seq_token(
                    req, int(self._dpos[slot]) + j)
        sampling = eng._sampling_active()
        outs, dprobs = self._propose_device(forced, n_forced, start,
                                            sampling)

        # ---- tail pages for the verify window ----------------------------
        limit = np.full(S, -1, np.int32)
        for slot in active:
            limit[slot] = self._limit(slot)
            eng._ensure_tail_pages(
                slot, min(int(eng._npos[slot]) + g, int(limit[slot])))

        # ---- verify ------------------------------------------------------
        ids = np.full((S, g + 1), eng.pad_id, np.int32)
        for slot in active:
            ids[slot, 0] = eng._last_tok[slot]
            for i in range(1, g + 1):
                j = lag[slot] + i - 1            # draft for index npos+i
                # lag <= 1 keeps j within the proposals (defensive clamp
                # against an adversarial/stubbed shorter propose)
                ids[slot, i] = outs[slot, min(j, outs.shape[1] - 1)]
        bt = np.full((S, Pn), NULL_PAGE, np.int32)
        for slot in active:
            bt[slot, :len(eng._bt[slot])] = eng._bt[slot]
        with eng.metrics.timer("verify_s"):
            if sampling:
                eng._pk, eng._pv, tgt, tprobs = self._verify_sampled(
                    eng.params, jnp.asarray(ids), eng._pk, eng._pv,
                    jnp.asarray(bt), jnp.asarray(eng._npos),
                    jnp.asarray(limit), eng._cos, eng._sin,
                    *eng.sampler.device_args()[:3])
                tprobs = np.asarray(tprobs)               # [S, g+1, V]
            else:
                eng._pk, eng._pv, tgt = self._verify(
                    eng.params, jnp.asarray(ids), eng._pk, eng._pv,
                    jnp.asarray(bt), jnp.asarray(eng._npos),
                    jnp.asarray(limit), eng._cos, eng._sin)
            tgt = np.asarray(tgt)                         # [S, g+1]

        # ---- accept + roll back ------------------------------------------
        emitted = {}
        for slot in active:
            req = eng.slots.owner(slot)
            p = int(eng._npos[slot])
            drafts = [int(ids[slot, i]) for i in range(1, g + 1)]
            if sampling and eng.sampler.any_sampling([slot]):
                a, commit = self._accept_sampled(req, slot, p, drafts,
                                                 lag[slot], dprobs, tprobs)
            else:
                # greedy rows keep EXACT-match acceptance (bit-identical
                # to sequential argmax, even inside a sampling batch)
                a = 0
                while a < g and drafts[a] == int(tgt[slot, a]):
                    a += 1
                commit = drafts[:a] + [int(tgt[slot, a])] if a < g \
                    else drafts + [int(tgt[slot, g])]
            k = 0
            for tok in commit:
                eng._emit(req, tok)
                k += 1
                if req.finished:
                    break
            eng._npos[slot] = p + k
            eng._last_tok[slot] = req.token_ids[-1]
            self._dpos[slot] = min(int(start[slot]) + steps,
                                   p + min(a, k) + 1, p + k)
            emitted[req.request_id] = commit[:k]
            eng.metrics.inc("draft_tokens_proposed", g)
            eng.metrics.inc("draft_tokens_accepted", min(a, k))
            eng.metrics.inc("tokens_generated", k)
            eng.metrics.observe("spec_commit_len", k)
            eng.metrics.observe("spec_acceptance_rate", min(a, k) / g)
            if req.finished:
                eng._retire(slot)
            else:
                self._rollback_tail(slot, p + k)
        eng.metrics.inc("spec_rounds")
        eng.metrics.observe("tokens_per_decode_step",
                            sum(len(v) for v in emitted.values()))
        return {"type": "spec_decode", "tokens": emitted}

    def _accept_sampled(self, req, slot, p, drafts, lag, dprobs, tprobs):
        """Rejection-sampling acceptance for one sampling row: draft
        token i (proposed from warped p_draft) is accepted with
        probability min(1, p_target/p_draft); the first rejection
        commits one token resampled from the adjusted residual
        normalize(max(0, p_target - p_draft)) and ends the round; a
        fully-accepted window commits a bonus token drawn from the
        target's own next distribution via the sequential (seed, pos)
        gumbel stream. Every committed token is exactly
        target-distributed — speculation changes the schedule, never
        the law — and when draft == target the acceptance ratio is 1,
        reducing the round to sequential seeded sampling (the parity
        test)."""
        g = self.g
        commit = []
        a = 0
        for i in range(1, g + 1):
            d = drafts[i - 1]
            j = min(lag + i - 1, dprobs.shape[1] - 1)
            pd = dprobs[slot, j]          # draft dist for index p+i
            pt = tprobs[slot, i - 1]      # target dist for index p+i
            ratio = float(pt[d]) / max(float(pd[d]), 1e-30)
            u = float(jax.random.uniform(
                _spec_key(req.seed, p + i, _ACCEPT_SALT), ()))
            if u < min(1.0, ratio):
                commit.append(d)
                a += 1
                continue
            residual = np.maximum(pt.astype(np.float64)
                                  - pd.astype(np.float64), 0.0)
            tot = float(residual.sum())
            if tot <= 0.0:
                # degenerate (draft dominates everywhere — only possible
                # through float rounding): fall back to the target dist
                residual, tot = pt.astype(np.float64), float(pt.sum())
            commit.append(_residual_draw(residual / tot, req.seed, p + i))
            self.eng.metrics.inc("spec_resamples")
            return a, commit
        # all g drafts accepted: bonus token from the target's next
        # distribution, drawn with the SAME gumbel-max + (seed, pos) key
        # sequential `generate(seeds=...)` would use at index p+g+1 —
        # log p_target is the warped logits up to a per-row constant, so
        # the argmax (hence the token) is identical
        pt = tprobs[slot, g]
        keys = gen._row_keys(np.asarray([req.seed], np.int32), p + g + 1)
        u = np.asarray(jax.vmap(lambda k_: jax.random.uniform(
            k_, pt.shape, jnp.float32, minval=1e-20, maxval=1.0))(keys))[0]
        logp = np.where(pt > 0, np.log(np.maximum(pt, 1e-30)), -np.inf)
        commit.append(int(np.argmax(logp - np.log(-np.log(u)))))
        return a, commit

    def _rollback_tail(self, slot, npos):
        """Truncate the slot's block table to the pages covering the
        committed positions [0, npos): window pages wholly past the new
        watermark return to the pool and their reservation is refunded.
        The rejected K/V inside the kept tail page stays as garbage that
        the next write-before-attend step overwrites."""
        eng = self.eng
        keep = (npos - 1) // eng.page_size + 1
        pages = eng._bt[slot]
        while len(pages) > keep:
            eng._alloc.release(pages.pop())
            eng._resv[slot] += 1
            eng._reserved_total += 1
            eng.metrics.inc("spec_pages_rewound")
