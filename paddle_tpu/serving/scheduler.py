"""Admission queue, prompt-length buckets, and the slot table.

Iteration-level scheduling (Orca, OSDI'22) needs three small host-side
pieces the engine composes every step:

  - `bucket_for`: prompts prefill at the next power-of-two length, so an
    arbitrary-length traffic mix compiles at most log2(max_len) prefill
    programs — compilation stays BOUNDED no matter what lengths arrive
    (the XLA analogue of vLLM's fixed block size: shape variety, not
    memory, is the scarce resource on TPU).
  - `AdmissionQueue`: FIFO of waiting requests; depth is exported as a
    gauge so saturation is visible.
  - `SlotTable`: S cache slots; admit() hands the lowest free slot to a
    request, retire() frees it for the next waiting request (the slot's
    KV range is NOT cleared — a prefill rewrites [0, bucket) and the
    write-before-attend decode order means stale tail positions are
    always overwritten before they are ever unmasked).
"""

from __future__ import annotations

from collections import deque

__all__ = ["bucket_for", "pages_for", "AdmissionQueue", "SlotTable"]


def bucket_for(n, min_bucket=16, max_bucket=None):
    """Smallest power-of-two >= n (floored at min_bucket, capped at
    max_bucket). One prefill program compiles per distinct bucket."""
    if n < 1:
        raise ValueError(f"bucket_for: need a non-empty prompt (n={n})")
    b = max(int(min_bucket), 1)
    while b < n:
        b *= 2
    if max_bucket is not None:
        if n > max_bucket:
            raise ValueError(
                f"prompt length {n} exceeds the largest bucket {max_bucket}")
        b = min(b, int(max_bucket))
    return b


def pages_for(prompt_len, max_new_tokens, page_size):
    """Worst-case page count for one request in the paged KV cache: KV is
    written for positions [0, prompt_len + max_new_tokens - 2] — the last
    emitted token is returned to the caller but its k/v is never written
    back (there is no further decode step to read it). This is what paged
    admission reserves up front, so a request admitted under FIFO can
    always finish without preemption."""
    last = int(prompt_len) + max(int(max_new_tokens), 1) - 2
    return max(last, 0) // int(page_size) + 1


class AdmissionQueue:
    """FIFO admission queue. Every mutation refreshes the queue-depth
    gauge on the shared metrics registry."""

    def __init__(self, metrics=None):
        self._q = deque()
        self._metrics = metrics

    def _gauge(self):
        if self._metrics is not None:
            self._metrics.set_gauge("queue_depth", len(self._q))

    def push(self, req):
        self._q.append(req)
        self._gauge()

    def pop(self):
        req = self._q.popleft()
        self._gauge()
        return req

    def peek(self):
        """Head of the queue without removing it (paged admission checks
        the head's page demand before committing a prefill step)."""
        return self._q[0]

    def peek_at(self, i):
        """Entry i without removing it (the paged engine's chunked-
        prefill anti-convoy scan: shorts may bypass queued longs while a
        chunk stream is in flight)."""
        return self._q[i]

    def pop_at(self, i):
        req = self._q[i]
        del self._q[i]
        self._gauge()
        return req

    def __len__(self):
        return len(self._q)

    def __bool__(self):
        return bool(self._q)


class SlotTable:
    """S KV-cache slots; tracks which request owns which slot."""

    def __init__(self, n_slots):
        if n_slots < 1:
            raise ValueError("need at least one slot")
        self.n_slots = n_slots
        self._free = list(range(n_slots - 1, -1, -1))  # pop() -> lowest slot
        self._owner = {}

    @property
    def free_count(self):
        return len(self._free)

    @property
    def active_slots(self):
        return sorted(self._owner)

    def owner(self, slot):
        return self._owner[slot]

    def admit(self, req):
        slot = self._free.pop()
        self._owner[slot] = req
        return slot

    def retire(self, slot):
        req = self._owner.pop(slot)
        self._free.append(slot)
        self._free.sort(reverse=True)
        return req

    def occupancy(self):
        return len(self._owner) / self.n_slots
