"""Disaggregated prefill/decode serving: KV-page migration between
role-restricted engines.

Prefill and decode have opposite hardware profiles — prefill is one big
compute-bound forward, decode is a memory-bound trickle — and inside one
`PagedEngine` they CONTEND: every long prompt stalls every decoding slot
for whole scheduler steps (the `prefill_stall_steps` gauge chunked
prefill only flattens, never removes). DistServe/Splitwise split the two
roles into separate workers so the interference dies at its root. The
block-table refactor (PR 8) made that split cheap to express here: a
sequence's KV cache IS a list of page ids, so a finished prefill moves
to the decode worker by shipping page CONTENTS + metadata, not by
re-computing anything.

  PrefillWorker (a `PagedEngine` whose decode path is switched off via
  the scheduler hooks) admits requests, runs prefills — prefix cache,
  chunked streaming and length buckets all unchanged — and on prompt
  completion EXTRACTS the slot's pages ([L, P, nkv, ps, hd] gathered
  along the pool's page axis; an int8 `QuantizedKVPage` pool ships its
  codes AND per-(page, kv-head) scales verbatim, no dequant round-trip),
  emits the first token, packs a `KVHandoff`, pushes it on the
  transport, and retires the slot — pages released, prefix registered,
  reservation refunded, exactly as a local retire.

  DecodeWorker (a `PagedEngine` that never prefills) polls the
  transport, and for each handoff allocates fresh pages, RE-SCATTERS the
  shipped contents into its own pool, seats the block table / position /
  last-token state, and decodes on. Because the page bytes are moved
  bit-exact (bf16 pages, or int8 codes + scales), the decode worker's
  continuation is token-for-token the monolithic engine's output.

  Transports: `LocalTransport` is an in-process queue that still
  round-trips every handoff through `KVHandoff.to_bytes()` — the whole
  path is tier-1-testable on CPU, serialization included.
  `StoreTransport` moves the same bytes through the native `TCPStore`
  for the 2-process rig (the CPU backend cannot run cross-process XLA
  programs, so the dryrun rig ships KV host-side; on a real TPU pod the
  same hand-off rides ICI/DCN device-to-device).

  `DisaggServer` wires one of each over a transport for the
  single-process case and mirrors completions back onto the submitted
  Request objects.

Extraction and re-scatter are two tiny jitted programs (`page_extract` /
`page_scatter`) that must stay COLLECTIVE-FREE — pure page-axis data
movement, pinned by the `analysis/presets.py` disagg goldens.
"""

from __future__ import annotations

import io
import json
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import generation as gen
from paddle_tpu.serving.paged_engine import PagedEngine
from paddle_tpu.serving.engine import Request
from paddle_tpu.serving.scheduler import pages_for

__all__ = ["KVHandoff", "LocalTransport", "StoreTransport",
           "PrefillWorker", "DecodeWorker", "DisaggServer"]


def _extract_pages_traced(pk, pv, pages):
    """Gather the K/V contents of `pages` (int32 [P]) out of the pool:
    every pool leaf — the bf16/f32 arrays, or an int8 `QuantizedKVPage`'s
    codes [L, num_pages, nkv, ps, hd] AND scales [L, num_pages, nkv] —
    has the page axis at axis 1, so one tree_map covers both layouts.
    Pure data movement: the disagg transfer programs are pinned
    collective-free."""
    def take(a):
        return jnp.take(a, pages, axis=1)

    return (jax.tree_util.tree_map(take, pk),
            jax.tree_util.tree_map(take, pv))


def _scatter_pages_traced(pk, pv, pages, data_k, data_v):
    """Write extracted page contents back into a (different) pool at
    fresh page ids `pages` [P] — the inverse of `_extract_pages_traced`,
    leaf-wise over the same axis-1 layout (int8 codes and scales land
    verbatim: no quantization round-trip on migration)."""
    def put(a, d):
        return a.at[:, pages].set(d)

    return (jax.tree_util.tree_map(put, pk, data_k),
            jax.tree_util.tree_map(put, pv, data_v))


def _leaf_dtype(name):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


class KVHandoff:
    """One finished prefill, packaged for migration: the request's
    identity + sampling params, the first (already emitted) token, and
    the slot's page contents. `pages_k`/`pages_v` mirror the pool leaf
    structure: plain ndarrays, or `QuantizedKVPage(q, scale)`."""

    def __init__(self, *, request_id, prompt_ids, max_new_tokens,
                 eos_token_id, temperature, top_p, top_k, seed, first,
                 pages_k, pages_v, sent_at=None):
        self.request_id = request_id
        self.prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.first = int(first)
        self.pages_k = pages_k
        self.pages_v = pages_v
        # wall-clock (time.time: comparable ACROSS processes, unlike
        # perf_counter) stamped at send; the receiver's admit computes
        # the hand-off latency histogram from it
        self.sent_at = sent_at

    @property
    def num_pages(self):
        leaf = jax.tree_util.tree_leaves(self.pages_k)[0]
        return int(leaf.shape[1])

    def _leaves(self):
        return (jax.tree_util.tree_leaves(self.pages_k)
                + jax.tree_util.tree_leaves(self.pages_v))

    def nbytes(self):
        return sum(x.nbytes for x in self._leaves())

    def to_bytes(self):
        """Self-describing wire format: json header (request metadata +
        per-leaf dtype/shape/length) then the raw leaf buffers. bf16
        rides as raw bytes + a dtype name (numpy cannot npz ml_dtypes
        arrays portably)."""
        leaves = [np.ascontiguousarray(np.asarray(x))
                  for x in self._leaves()]
        meta = {
            "request_id": self.request_id,
            "max_new_tokens": self.max_new_tokens,
            "eos_token_id": self.eos_token_id,
            "temperature": self.temperature,
            "top_p": self.top_p,
            "top_k": self.top_k,
            "seed": self.seed,
            "first": self.first,
            "sent_at": self.sent_at,
            "prompt_len": int(self.prompt_ids.size),
            "quantized": isinstance(self.pages_k, gen.QuantizedKVPage),
            "leaves": [{"dtype": x.dtype.name, "shape": list(x.shape),
                        "nbytes": x.nbytes} for x in leaves],
        }
        head = json.dumps(meta).encode()
        out = io.BytesIO()
        out.write(len(head).to_bytes(8, "little"))
        out.write(head)
        out.write(self.prompt_ids.tobytes())
        for x in leaves:
            out.write(x.tobytes())
        return out.getvalue()

    @classmethod
    def from_bytes(cls, blob):
        hlen = int.from_bytes(blob[:8], "little")
        meta = json.loads(blob[8:8 + hlen].decode())
        off = 8 + hlen
        n = meta["prompt_len"]
        prompt = np.frombuffer(blob, np.int32, count=n, offset=off).copy()
        off += prompt.nbytes
        leaves = []
        for d in meta["leaves"]:
            dt = _leaf_dtype(d["dtype"])
            count = d["nbytes"] // dt.itemsize
            leaves.append(np.frombuffer(blob, dt, count=count, offset=off)
                          .reshape(d["shape"]).copy())
            off += d["nbytes"]
        if meta["quantized"]:
            pages_k = gen.QuantizedKVPage(leaves[0], leaves[1])
            pages_v = gen.QuantizedKVPage(leaves[2], leaves[3])
        else:
            pages_k, pages_v = leaves[0], leaves[1]
        return cls(request_id=meta["request_id"], prompt_ids=prompt,
                   max_new_tokens=meta["max_new_tokens"],
                   eos_token_id=meta["eos_token_id"],
                   temperature=meta["temperature"], top_p=meta["top_p"],
                   top_k=meta["top_k"], seed=meta["seed"],
                   first=meta["first"], pages_k=pages_k, pages_v=pages_v,
                   sent_at=meta["sent_at"])


class LocalTransport:
    """In-process hand-off queue. Every payload still round-trips through
    `KVHandoff.to_bytes()` so tier-1 exercises the exact byte path the
    2-process `StoreTransport` ships."""

    def __init__(self):
        self._q = deque()

    def send(self, blob):
        self._q.append(blob)

    def recv(self):
        return self._q.popleft() if self._q else None

    @property
    def pending(self):
        return len(self._q)


class StoreTransport:
    """TCPStore-backed byte transport for the 2-process dryrun rig: the
    sender publishes numbered messages under `channel/` and bumps a
    counter; the receiver polls the counter non-blockingly (`add(key, 0)`
    creates-or-reads) and fetches in order. One direction per instance."""

    def __init__(self, store, channel="disagg"):
        self.store = store
        self.channel = channel
        self._sent = 0
        self._seen = 0

    def send(self, blob):
        self.store.set(f"{self.channel}/m{self._sent}", blob)
        self._sent += 1
        self.store.add(f"{self.channel}/n", 1)

    def recv(self):
        n = int(self.store.add(f"{self.channel}/n", 0))
        if self._seen >= n:
            return None
        blob = self.store.get(f"{self.channel}/m{self._seen}")
        self._seen += 1
        return blob

    @property
    def pending(self):
        return int(self.store.add(f"{self.channel}/n", 0)) - self._seen


class PrefillWorker(PagedEngine):
    """A `PagedEngine` restricted to the PREFILL role via the scheduler
    hooks: `_decodable_slots` is empty so `_step_action` only ever
    prefills (monolithic or chunk-streamed), and a completed prompt is
    extracted, shipped on the transport, and retired instead of staying
    seated for decode. Prefix cache, chunked prefill, buckets and page
    accounting are all the base engine's."""

    def __init__(self, params, args, *, transport, **kw):
        if kw.get("draft_params") is not None:
            raise ValueError("disaggregated workers do not run "
                             "speculative decoding (the draft mirror "
                             "belongs to the decode role)")
        self.transport = transport
        super().__init__(params, args, **kw)

    def _setup_device_state(self):
        super()._setup_device_state()
        # extraction never donates: the pool must survive the gather
        # (the slot retires on the HOST side after the ship)
        self._page_extract = self._sharded(
            _extract_pages_traced,
            in_specs=(self._poolspec, self._poolspec, None),
            out_specs=(self._poolspec, self._poolspec),
            donate=())

    def _decodable_slots(self):
        return []

    def _build_handoff(self, req, slot, first):
        pages = np.asarray(self._bt[slot], np.int32)
        with self.metrics.timer("page_extract_s"):
            pk, pv = self._page_extract(self._pk, self._pv,
                                        jnp.asarray(pages))
        pk = jax.tree_util.tree_map(np.asarray, pk)
        pv = jax.tree_util.tree_map(np.asarray, pv)
        return KVHandoff(
            request_id=req.request_id, prompt_ids=req.prompt_ids,
            max_new_tokens=req.max_new_tokens,
            eos_token_id=req.eos_token_id, temperature=req.temperature,
            top_p=req.top_p, top_k=req.top_k, seed=req.seed, first=first,
            pages_k=pk, pages_v=pv, sent_at=time.time())

    def _complete_prefill(self, req, slot, bucket, first, n):
        ev = super()._complete_prefill(req, slot, bucket, first, n)
        if not req.finished:
            pkg = self._build_handoff(req, slot, first)
            self.transport.send(pkg.to_bytes())
            self.metrics.inc("handoffs_sent")
            self.metrics.inc("handoff_pages", pkg.num_pages)
            self.metrics.inc("handoff_bytes", pkg.nbytes())
            # release the refcounts / refund the reservation on THIS
            # side — the decode worker owns the sequence now. _retire
            # also registers the prompt's pages in the local prefix
            # cache, so a later identical prompt still hits.
            self._retire(slot)
            ev = dict(ev, type="prefill_handoff")
        return ev


class DecodeWorker(PagedEngine):
    """A `PagedEngine` restricted to the DECODE role: it never admits
    from its own queue (`_can_prefill` is False); instead each step
    drains the transport, seating every handoff that fits — fresh pages
    allocated, shipped contents re-scattered, block table / position /
    last-token state restored — then runs the normal batched paged
    decode over all seated slots. `completion_cb(req)` fires at each
    request's retirement (the `DisaggServer` mirror hook)."""

    def __init__(self, params, args, *, transport, completion_cb=None,
                 **kw):
        if kw.get("draft_params") is not None:
            raise ValueError("disaggregated workers do not run "
                             "speculative decoding (the draft has no "
                             "prompt mirror on the decode side)")
        self.transport = transport
        self.completion_cb = completion_cb
        self._inbox = deque()
        super().__init__(params, args, **kw)

    def _setup_device_state(self):
        super()._setup_device_state()
        donate = self._donate_enabled()
        self._page_scatter = self._sharded(
            _scatter_pages_traced,
            in_specs=(self._poolspec, self._poolspec, None,
                      self._poolspec, self._poolspec),
            out_specs=(self._poolspec, self._poolspec),
            donate=(0, 1) if donate else ())

    def _can_prefill(self):
        return False

    def _can_admit(self, pkg):
        if not self.slots.free_count:
            return False
        n = int(pkg.prompt_ids.size)
        total = pages_for(n, pkg.max_new_tokens, self.page_size)
        # fresh pages for the shipped contents, plus the same decode-tail
        # reservation a local admission would post
        return total <= self._alloc.available - self._reserved_total

    def admit_handoff(self, pkg):
        """Seat one migrated sequence; returns its (new, local) Request.
        The caller must have checked `_can_admit`."""
        n = int(pkg.prompt_ids.size)
        req = Request(pkg.prompt_ids, pkg.max_new_tokens,
                      eos_token_id=pkg.eos_token_id,
                      request_id=pkg.request_id,
                      temperature=pkg.temperature, top_p=pkg.top_p,
                      top_k=pkg.top_k, seed=pkg.seed)
        req.submit_time = time.perf_counter()
        req.submit_step = self.step_count
        # the first token was emitted on the prefill side; seed the
        # emission count so eos/length accounting continues from it
        req.token_ids = [pkg.first]
        slot = self._admit(req)
        n_pages = pkg.num_pages
        pages = [self._alloc.alloc() for _ in range(n_pages)]
        with self.metrics.timer("page_scatter_s"):
            self._pk, self._pv = self._page_scatter(
                self._pk, self._pv, jnp.asarray(pages, jnp.int32),
                jax.tree_util.tree_map(jnp.asarray, pkg.pages_k),
                jax.tree_util.tree_map(jnp.asarray, pkg.pages_v))
        self._bt[slot] = pages
        resv = pages_for(n, pkg.max_new_tokens, self.page_size) - n_pages
        self._resv[slot] = resv
        self._reserved_total += resv
        # npos = next KV write position = the prompt length (the first
        # generated token's KV lands on the next decode step, exactly as
        # after a local prefill)
        self._npos[slot] = n
        self._last_tok[slot] = pkg.first
        self.metrics.inc("handoffs_admitted")
        if pkg.sent_at is not None:
            self.metrics.observe("handoff_latency_s",
                                 max(0.0, time.time() - pkg.sent_at))
        return req

    def _drain_inbox(self):
        while True:
            blob = self.transport.recv()
            if blob is None:
                break
            self._inbox.append(KVHandoff.from_bytes(blob))
        admitted = 0
        while self._inbox and self._can_admit(self._inbox[0]):
            self.admit_handoff(self._inbox.popleft())
            admitted += 1
        if self._inbox:
            self.metrics.inc("handoff_defer_steps")
        return admitted

    def _step_action(self):
        admitted = self._drain_inbox()
        if self._decodable_slots():
            ev = self._decode_step()
            if admitted:
                ev = dict(ev, admitted=admitted)
            return ev
        if admitted:
            return {"type": "handoff_admit", "count": admitted}
        return {"type": "idle"}

    @property
    def busy(self):
        return bool(self.slots.active_slots or self._inbox)

    def _retire(self, slot):
        req = self.slots.owner(slot)
        super()._retire(slot)
        if req is not None and self.completion_cb is not None:
            self.completion_cb(req)


class DisaggServer:
    """Single-process wiring: one PrefillWorker + one DecodeWorker over a
    `LocalTransport` (each with its own page pool, as two hosts would
    have). `submit()` goes to the prefill side; completions are mirrored
    back onto the submitted Request objects, so callers see the same
    surface a monolithic engine gives them."""

    def __init__(self, params, args, *, transport=None, **kw):
        self.transport = transport if transport is not None \
            else LocalTransport()
        self.prefill = PrefillWorker(params, args,
                                     transport=self.transport, **kw)
        self.decode = DecodeWorker(params, args, transport=self.transport,
                                   completion_cb=self._on_complete, **kw)
        self._orig = {}

    def _on_complete(self, twin):
        orig = self._orig.pop(twin.request_id, None)
        if orig is None or orig is twin:
            return
        # twin.token_ids[0] is the first token the prefill side already
        # emitted into orig — mirror the full list, not append
        orig.token_ids = list(twin.token_ids)
        orig.finished = twin.finished
        orig.finish_reason = twin.finish_reason
        orig.finish_time = twin.finish_time

    def submit(self, req):
        if not isinstance(req, Request):
            req = Request(req)
        self._orig[req.request_id] = req
        return self.prefill.submit(req)

    def step(self):
        self.prefill.step()
        self.decode.step()

    @property
    def busy(self):
        return bool(self.prefill.queue or self.prefill.slots.active_slots
                    or self.prefill._chunk_streams or self.transport.pending
                    or self.decode.busy)

    def run_until_idle(self):
        stalled = 0
        while self.busy:
            before = (self.prefill.step_count + self.decode.step_count,
                      len(self.decode._inbox))
            self.step()
            progressed = (self.prefill.queue
                          or self.prefill.slots.active_slots
                          or self.prefill._chunk_streams
                          or self.transport.pending
                          or self.decode.slots.active_slots)
            stalled = 0 if progressed else stalled + 1
            if stalled > 8 and self.decode._inbox:
                pkg = self.decode._inbox[0]
                raise RuntimeError(
                    f"decode worker cannot seat handoff "
                    f"{pkg.request_id!r}: needs "
                    f"{pages_for(pkg.prompt_ids.size, pkg.max_new_tokens, self.decode.page_size)} "
                    f"pages, pool has {self.decode._alloc.available} "
                    f"available")
            _ = before

    def serve(self, requests):
        reqs = [self.submit(r) for r in requests]
        self.run_until_idle()
        return reqs
