"""Continuous-batching LLM serving engine with a slot-based KV cache.

The offline `models/generation.generate` path decodes a FIXED batch: one
straggler holds every row, finished rows burn decode FLOPs emitting pads,
and new requests wait for the whole batch to drain. This engine applies
iteration-level scheduling (Orca, OSDI'22) over the slot/block-managed
cache idea (vLLM's PagedAttention, SOSP'23), assembled from the PR-1
decode machinery:

  - ONE fixed KV cache `[L, S, nkv, max_len, hd]` (heads-major, the
    layout the Pallas decode-attention kernel consumes) where the batch
    axis is S SLOTS, each owned by at most one in-flight request;
  - every `step()` either PREFILLS the next queued request into a free
    slot (prompt right-padded to a power-of-two length bucket —
    compilation stays bounded at #buckets prefill programs; the
    next-token logits are gathered at the request's true last token) or
    runs ONE batched decode step across all S slots with a PER-ROW
    position vector (`models/generation.decode_step`'s pos-vector form:
    per-row RoPE, per-row cache writes, per-row valid-prefix masking in
    both the jnp fallback and the Pallas decode kernel);
  - rows that emit their EOS (or hit max_new_tokens) RETIRE immediately:
    the slot returns to the table and the next waiting request is
    admitted on a later step — no drain barrier. Slot caches are never
    cleared: a prefill rewrites the whole slot, and decode's
    write-before-attend order means stale tail positions are always
    overwritten before the position mask ever exposes them.

Greedy decoding by default (the scheduler retires rows on exact token
identity, so continuous-batched output is token-for-token identical to
sequential `generate` — tested); per-request sampling
(temperature/top-p/top-k + per-request seeds, `Request(...)`) rides the
same decode program as traced per-row vectors — greedy rows stay
bit-exact argmax inside a mixed batch, and greedy-only traffic never
compiles the sampling ops. Weight-only int8 trees from
`generation.quantize_params` serve unchanged: every matmul inside the
traced step streams through the fused dequant-matmul dispatch.

Host/device split: the scheduler (queue, slot table, retire/admit,
streaming callbacks, wall-clock metrics) runs in Python between steps;
the two traced programs (per-bucket prefill, one decode) contain no
wall-clock reads and re-compile only when a NEW bucket shape arrives —
compile counts are metered at trace time (`serving/metrics.py`).

`serving/paged_engine.PagedEngine` subclasses this scheduler loop but
swaps the per-slot stripes for a paged KV cache (page pool + block
tables + hash-based prefix reuse) — far more concurrent requests per
byte of KV HBM; the stripe engine remains the simple baseline and the
equal-HBM comparison leg in `bench.py --serving`.
"""

from __future__ import annotations

import functools
import itertools
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import generation as gen
from paddle_tpu.models import llama_functional as lf
from paddle_tpu.serving.metrics import Metrics
from paddle_tpu.serving.sampler import SlotSampler, pick as _pick
from paddle_tpu.serving.scheduler import AdmissionQueue, SlotTable, bucket_for

__all__ = ["Request", "Engine"]

_req_ids = itertools.count()


class Request:
    """One generation request.

    stream_cb(request, token_id, finished) fires once per generated token,
    in emission order, from the host scheduler (never inside traced code).
    After completion: `token_ids` (generated tokens, incl. the EOS if one
    was emitted), `finish_reason` ('eos' | 'length'), `ttft_s` (first
    EMITTED token), `prefill_done_s` (prompt fully in the KV cache —
    under chunked prefill the two diverge, see Engine._record_prefill_done).

    Sampling: temperature 0 (default) is exactly greedy; temperature > 0
    samples with optional nucleus top_p and top-k cutoffs. `seed` fixes
    the request's own PRNG stream — the sampled tokens depend only on
    (seed, position), not on which other requests share its batch steps
    (default: the request id, so trace replays are deterministic). All
    four are PER-REQUEST and traced: a mixed greedy/sampling batch runs
    one program, greedy rows staying bit-exact argmax.
    """

    def __init__(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
                 stream_cb=None, request_id=None, temperature=0.0,
                 top_p=1.0, top_k=0, seed=None):
        self.prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))
        self.stream_cb = stream_cb
        self.request_id = (next(_req_ids) if request_id is None
                           else request_id)
        self.temperature = float(temperature)
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        self.top_p = float(top_p)
        self.top_k = int(top_k)
        if seed is None:
            try:
                seed = int(self.request_id)
            except (TypeError, ValueError):
                # stable across processes (hash() of str is randomized
                # per interpreter — it would break deterministic replays)
                import zlib

                seed = zlib.crc32(str(self.request_id).encode())
        # one normalization point: every consumer (engine programs AND a
        # user passing req.seed to generate(seeds=...)) sees the same
        # non-negative int32
        self.seed = int(seed) & 0x7FFFFFFF
        self.token_ids = []
        self.finished = False
        self.finish_reason = None
        self.submit_time = None
        self.submit_step = None
        self.first_token_time = None
        self.finish_time = None
        self.ttft_s = None
        self.ttft_steps = None
        self.prefill_done_s = None
        self.prefill_done_steps = None

    def output_ids(self):
        """prompt + generated tokens (the sequential-generate row shape,
        minus its trailing pads)."""
        return np.concatenate(
            [self.prompt_ids, np.asarray(self.token_ids, np.int32)])


def _prefill_traced(params, ids, true_len, ck, cv, slot, cos, sin, temp,
                    top_p, top_k, seeds, *, args, metrics, sample=False,
                    counter="prefill_compiles"):
    # runs once per COMPILE (trace time), not per call — see metrics.py
    metrics.inc(counter)
    L = ck.shape[0]
    sck = jnp.zeros((L, 1) + ck.shape[2:], ck.dtype)
    scv = jnp.zeros_like(sck)
    logits, sck, scv = gen._forward_cached(
        params, ids, sck, scv, 0, cos, sin, args, last_idx=true_len - 1)
    first = _pick(logits, sample, temp, top_p, top_k, seeds, true_len)[0]
    ck = jax.lax.dynamic_update_slice_in_dim(ck, sck, slot, axis=1)
    cv = jax.lax.dynamic_update_slice_in_dim(cv, scv, slot, axis=1)
    return ck, cv, first


def _decode_traced(params, tokens, ck, cv, pos, cos, sin, temp, top_p,
                   top_k, seeds, *, args, metrics, sample=False,
                   counter="decode_compiles"):
    metrics.inc(counter)
    logits, ck, cv = gen._forward_cached(
        params, tokens[:, None], ck, cv, pos, cos, sin, args)
    # the sampled token lands at sequence index pos+1 — the same
    # (seed, position) stream the offline `generate(seeds=...)` draws from
    return ck, cv, _pick(logits, sample, temp, top_p, top_k, seeds, pos + 1)


class Engine:
    """Continuous-batching serving engine over a Llama functional param
    tree (float or `quantize_params` int8).

    max_slots: S — concurrent in-flight requests (the decode batch).
    max_len:   per-slot KV capacity; prompt_len + max_new_tokens must stay
               within it. On TPU pick a multiple of 128 so the Pallas
               decode-attention fast path stays eligible.
    min_bucket: smallest prefill length bucket (power-of-two ladder up to
               max_len).
    """

    def __init__(self, params, args, *, max_slots=4, max_len=256,
                 min_bucket=16, pad_id=0, metrics=None, donate_steps=None):
        self.params = params
        self.args = args
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.min_bucket = int(min_bucket)
        self.pad_id = int(pad_id)
        self.metrics = metrics if metrics is not None else Metrics()
        # donate_steps: None = auto (donate the KV buffers on TPU only);
        # True/False force it. The static donation audit forces True on
        # CPU so the lowered programs it inspects carry the same aliasing
        # the production TPU programs do.
        self._donate_steps = donate_steps

        self.queue = AdmissionQueue(self.metrics)
        self.slots = SlotTable(self.max_slots)
        self._npos = np.zeros(self.max_slots, np.int32)   # next write pos
        self._last_tok = np.full(self.max_slots, self.pad_id, np.int32)
        # per-slot sampling state (greedy defaults; set at admission)
        self.sampler = SlotSampler(self.max_slots)
        self.step_count = 0
        self._stall_steps = 0     # decode work delayed by a prefill step
        self._setup_device_state()

    def _donate_enabled(self):
        """Whether step programs donate their threaded-through buffers."""
        if self._donate_steps is not None:
            return bool(self._donate_steps)
        return jax.default_backend() == "tpu"

    def _setup_device_state(self):
        """Allocate the KV cache buffers + compile wrappers (subclass
        hook: the paged engine replaces the per-slot stripes with a page
        pool here)."""
        args = self.args
        L = lf.stack_leading_dim(self.params["layers"])
        hd = args.hidden_size // args.num_heads
        cache_dtype = self.params["embedding"].dtype
        self._ck = jnp.zeros(
            (L, self.max_slots, args.num_kv_heads, self.max_len, hd),
            cache_dtype)
        self._cv = jnp.zeros_like(self._ck)
        self._cos, self._sin = lf.rope_tables(self.max_len, hd,
                                              args.rope_theta)

        # donate the KV cache buffers: the engine threads ck/cv through
        # every step and immediately drops the old arrays, so XLA aliases
        # input to output instead of materializing a fresh cache copy per
        # step (on the TPU bench shape that copy is ~1 GB/step). CPU/other
        # backends don't implement donation — skip it there to avoid a
        # warning per compile (donate_steps=True forces it for audits).
        donate = self._donate_enabled()
        self._prefill = jax.jit(
            functools.partial(_prefill_traced, args=args,
                              metrics=self.metrics),
            donate_argnums=(3, 4) if donate else (),
            static_argnames=("sample",))
        self._decode = jax.jit(
            functools.partial(_decode_traced, args=args,
                              metrics=self.metrics),
            donate_argnums=(2, 3) if donate else (),
            static_argnames=("sample",))

    # -- admission ----------------------------------------------------------
    def submit(self, req):
        """Queue a Request (or raw prompt ids). Returns the Request."""
        if not isinstance(req, Request):
            req = Request(req)
        n = int(req.prompt_ids.size)
        bucket_for(n, self.min_bucket, self.max_len)  # length must fit
        if n + req.max_new_tokens > self.max_len + 1:
            raise ValueError(
                f"request needs {n} prompt + {req.max_new_tokens} new "
                f"tokens but the slot capacity is max_len={self.max_len}")
        req.submit_time = time.perf_counter()
        req.submit_step = self.step_count
        self.queue.push(req)
        self.metrics.inc("requests_submitted")
        return req

    # -- the iteration-level scheduler --------------------------------------
    def step(self):
        """One engine iteration: admit-and-prefill if a request is waiting
        and a slot is free (paged engines also require page capacity),
        else one batched decode step over all active slots, else idle.
        Returns a small event dict."""
        ev = self._step_action()
        self.step_count += 1
        self.metrics.observe("slot_occupancy", self.slots.occupancy())
        self.metrics.set_gauge("active_slots", len(self.slots.active_slots))
        return ev

    def _step_action(self):
        """Pick and run this iteration's unit of work (subclass hook: the
        paged engine interleaves chunked-prefill streams and swaps decode
        for speculate-and-verify here)."""
        if self._can_prefill():
            self._note_prefill_stall()
            return self._prefill_step()
        if self._decodable_slots():
            return self._decode_step()
        return {"type": "idle"}

    def _note_prefill_stall(self):
        """Account one prefill-shaped step taken while decodable slots
        sat waiting — the `prefill_stall_steps` gauge chunked prefill
        exists to flatten (a monolithic long prefill stalls every
        decoding slot for its whole wall time; a chunk stalls them for
        one bounded chunk)."""
        if self._decodable_slots():
            self._stall_steps += 1
            self.metrics.set_gauge("prefill_stall_steps", self._stall_steps)

    def _decodable_slots(self):
        """Slots eligible for a batched decode step (subclass hook: the
        paged engine excludes slots whose prompt is still mid-chunked-
        prefill)."""
        return self.slots.active_slots

    def _can_prefill(self):
        """True when the next queued request can be admitted this step
        (subclass hook: the paged engine also checks page-pool capacity
        for the queue head)."""
        return bool(self.queue and self.slots.free_count)

    def run_until_idle(self):
        """Drive step() until every queued/active request completes."""
        while self.queue or self.slots.active_slots:
            self.step()

    def serve(self, requests):
        """Convenience: submit all, run to completion, return them."""
        reqs = [self.submit(r) for r in requests]
        self.run_until_idle()
        return reqs

    def replay(self, trace):
        """Replay an arrival trace (tools/serving_trace.py): each entry
        {'arrival_step', 'prompt', 'max_new_tokens'[, 'eos_token_id']} is
        submitted once the engine reaches its arrival step; idle steps
        advance virtual time between sparse arrivals. Returns Requests in
        trace order."""
        pending = sorted(trace, key=lambda t: t["arrival_step"])
        out = {}
        i = 0
        while i < len(pending) or self.queue or self.slots.active_slots:
            while (i < len(pending)
                   and pending[i]["arrival_step"] <= self.step_count):
                t = pending[i]
                req = Request(t["prompt"], t["max_new_tokens"],
                              eos_token_id=t.get("eos_token_id"),
                              request_id=t.get("request_id"),
                              temperature=t.get("temperature", 0.0),
                              top_p=t.get("top_p", 1.0),
                              top_k=t.get("top_k", 0),
                              seed=t.get("seed"))
                out[id(t)] = self.submit(req)
                i += 1
            self.step()
        return [out[id(t)] for t in trace]

    def reset(self):
        """Forget all requests/slots (keeps compiled programs AND compile
        counters; per-run metrics are cleared) — benchmark warmup then
        timed replay on one engine without recompiling."""
        if self.queue or self.slots.active_slots:
            raise RuntimeError("reset() with requests still in flight")
        # every trace-time compile counter survives: warm replay compiles,
        # reset, timed replay hits the jit cache — wiping any of these
        # would report 0 programs built for the timed run's artifacts
        self.metrics.reset(keep_counters=("prefill_compiles",
                                          "decode_compiles",
                                          "verify_compiles",
                                          "draft_propose_compiles",
                                          "draft_prefill_compiles"))
        self.queue = AdmissionQueue(self.metrics)
        self.slots = SlotTable(self.max_slots)
        self._npos[:] = 0
        self._last_tok[:] = self.pad_id
        self.sampler.reset()
        self.step_count = 0
        self._stall_steps = 0

    # -- internals ----------------------------------------------------------
    def _admit(self, req):
        """Hand the queue head a slot and load its sampling params."""
        slot = self.slots.admit(req)
        self.sampler.admit(slot, req)
        return slot

    def _sampling_active(self):
        """True when any slot in the decode batch samples — selects the
        decode program variant (greedy-only traffic never compiles the
        sampling ops). Scoped to the DECODABLE slots: a sampling request
        still mid-chunked-prefill must not push the greedy rows' decode
        steps onto the sampling program."""
        return self.sampler.any_sampling(self._decodable_slots())

    def _record_prefill_done(self, req):
        """The prompt is fully in the target's KV cache. This is NOT
        TTFT: under chunked prefill the final chunk stashes the first
        token but emission waits for the stream to finish (with
        speculation the draft mirror may still be catching up window by
        window), so the two diverge by whole engine steps. Telemetry
        keeps both — `ttft_s` is what a client observes, `prefill_done_s`
        is what the prefill path costs. Idempotent: the monolithic path
        reaches here again via _complete_prefill."""
        if req.prefill_done_s is not None:
            return
        now = time.perf_counter()
        req.prefill_done_s = now - req.submit_time
        req.prefill_done_steps = self.step_count - req.submit_step
        self.metrics.observe("prefill_done_s", req.prefill_done_s)
        self.metrics.observe("prefill_done_steps", req.prefill_done_steps)

    def _record_first_token(self, req):
        now = time.perf_counter()
        req.first_token_time = now
        # TTFT at the first EMITTED token (not prefill completion), in
        # wall-clock seconds AND engine steps: steps are the
        # load-independent scheduling-delay unit arrival traces are written
        # in; seconds are what ROADMAP 2's p99 acceptance is measured in
        req.ttft_s = now - req.submit_time
        req.ttft_steps = self.step_count - req.submit_step
        self.metrics.observe("ttft_s", req.ttft_s)
        self.metrics.observe("ttft_steps", req.ttft_steps)

    def _prefill_step(self):
        req = self.queue.pop()
        slot = self._admit(req)
        n = int(req.prompt_ids.size)
        bucket, first = self._prefill_device(req, slot, n)
        return self._complete_prefill(req, slot, bucket, first, n)

    def _complete_prefill(self, req, slot, bucket, first, n):
        """Book-keep a finished prompt prefill: TTFT, counters, position,
        the first emitted token (shared by the monolithic path and the
        paged engine's final chunk)."""
        self._record_prefill_done(req)
        self._record_first_token(req)
        self.metrics.inc("prefills")
        self.metrics.inc("tokens_generated")
        self._npos[slot] = n
        self._last_tok[slot] = first
        self._emit(req, first)
        if req.finished:
            self._retire(slot)
        return {"type": "prefill", "request_id": req.request_id,
                "slot": slot, "bucket": bucket, "token": first}

    def _prefill_device(self, req, slot, n):
        """Run the device half of a prefill (subclass hook). Returns
        (bucket, first_token)."""
        bucket = bucket_for(n, self.min_bucket, self.max_len)
        padded = np.full((1, bucket), self.pad_id, np.int32)
        padded[0, :n] = req.prompt_ids
        with self.metrics.timer("prefill_s"):
            self._ck, self._cv, first = self._prefill(
                self.params, jnp.asarray(padded), jnp.int32(n),
                self._ck, self._cv, jnp.int32(slot), self._cos, self._sin,
                jnp.float32(req.temperature), jnp.float32(req.top_p),
                jnp.int32(req.top_k),
                jnp.asarray([req.seed], jnp.int32),
                sample=req.temperature > 0)
            first = int(first)
        return bucket, first

    def _decode_step(self):
        active = self._decodable_slots()
        nxt = self._decode_device(active)
        emitted = {}
        for slot in active:
            self._npos[slot] += 1
            tok = int(nxt[slot])
            self._last_tok[slot] = tok
            req = self.slots.owner(slot)
            self._emit(req, tok)
            emitted[req.request_id] = tok
            if req.finished:
                self._retire(slot)
        self.metrics.inc("decode_steps")
        self.metrics.inc("tokens_generated", len(active))
        self.metrics.observe("tokens_per_decode_step", len(active))
        return {"type": "decode", "tokens": emitted}

    def _sampling_args(self):
        return self.sampler.device_args()

    def _decode_device(self, active):
        """Run the device half of one batched decode step (subclass
        hook). Returns the next-token array [S] on host."""
        with self.metrics.timer("decode_step_s"):
            self._ck, self._cv, nxt = self._decode(
                self.params, jnp.asarray(self._last_tok), self._ck,
                self._cv, jnp.asarray(self._npos), self._cos, self._sin,
                *self._sampling_args(), sample=self._sampling_active())
        return np.asarray(nxt)

    def _emit(self, req, token):
        req.token_ids.append(token)
        finished, reason = False, None
        if req.eos_token_id is not None and token == req.eos_token_id:
            finished, reason = True, "eos"
        elif len(req.token_ids) >= req.max_new_tokens:
            finished, reason = True, "length"
        if req.stream_cb is not None:
            req.stream_cb(req, token, finished)
        if finished:
            req.finished = True
            req.finish_reason = reason
            req.finish_time = time.perf_counter()
            self.metrics.inc("requests_finished")

    def _retire(self, slot):
        self.slots.retire(slot)
        self._npos[slot] = 0
        self._last_tok[slot] = self.pad_id
        self.sampler.clear(slot)
