"""Continuous-batching LLM serving engine with a slot-based KV cache.

The offline `models/generation.generate` path decodes a FIXED batch: one
straggler holds every row, finished rows burn decode FLOPs emitting pads,
and new requests wait for the whole batch to drain. This engine applies
iteration-level scheduling (Orca, OSDI'22) over the slot/block-managed
cache idea (vLLM's PagedAttention, SOSP'23), assembled from the PR-1
decode machinery:

  - ONE fixed KV cache `[L, S, nkv, max_len, hd]` (heads-major, the
    layout the Pallas decode-attention kernel consumes) where the batch
    axis is S SLOTS, each owned by at most one in-flight request;
  - every `step()` either PREFILLS the next queued request into a free
    slot (prompt right-padded to a power-of-two length bucket —
    compilation stays bounded at #buckets prefill programs; the
    next-token logits are gathered at the request's true last token) or
    runs ONE batched decode step across all S slots with a PER-ROW
    position vector (`models/generation.decode_step`'s pos-vector form:
    per-row RoPE, per-row cache writes, per-row valid-prefix masking in
    both the jnp fallback and the Pallas decode kernel);
  - rows that emit their EOS (or hit max_new_tokens) RETIRE immediately:
    the slot returns to the table and the next waiting request is
    admitted on a later step — no drain barrier. Slot caches are never
    cleared: a prefill rewrites the whole slot, and decode's
    write-before-attend order means stale tail positions are always
    overwritten before the position mask ever exposes them.

Greedy decoding (the scheduler retires rows on exact token identity, so
continuous-batched output is token-for-token identical to sequential
`generate` — tested). Weight-only int8 trees from
`generation.quantize_params` serve unchanged: every matmul inside the
traced step streams through the fused dequant-matmul dispatch.

Host/device split: the scheduler (queue, slot table, retire/admit,
streaming callbacks, wall-clock metrics) runs in Python between steps;
the two traced programs (per-bucket prefill, one decode) contain no
wall-clock reads and re-compile only when a NEW bucket shape arrives —
compile counts are metered at trace time (`serving/metrics.py`).

`serving/paged_engine.PagedEngine` subclasses this scheduler loop but
swaps the per-slot stripes for a paged KV cache (page pool + block
tables + hash-based prefix reuse) — far more concurrent requests per
byte of KV HBM; the stripe engine remains the simple baseline and the
equal-HBM comparison leg in `bench.py --serving`.
"""

from __future__ import annotations

import functools
import itertools
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import generation as gen
from paddle_tpu.models import llama_functional as lf
from paddle_tpu.serving.metrics import Metrics
from paddle_tpu.serving.scheduler import AdmissionQueue, SlotTable, bucket_for

__all__ = ["Request", "Engine"]

_req_ids = itertools.count()


class Request:
    """One generation request.

    stream_cb(request, token_id, finished) fires once per generated token,
    in emission order, from the host scheduler (never inside traced code).
    After completion: `token_ids` (generated tokens, incl. the EOS if one
    was emitted), `finish_reason` ('eos' | 'length'), `ttft_s`.
    """

    def __init__(self, prompt_ids, max_new_tokens=32, eos_token_id=None,
                 stream_cb=None, request_id=None):
        self.prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_token_id = (None if eos_token_id is None
                             else int(eos_token_id))
        self.stream_cb = stream_cb
        self.request_id = (next(_req_ids) if request_id is None
                           else request_id)
        self.token_ids = []
        self.finished = False
        self.finish_reason = None
        self.submit_time = None
        self.submit_step = None
        self.first_token_time = None
        self.finish_time = None
        self.ttft_s = None
        self.ttft_steps = None

    def output_ids(self):
        """prompt + generated tokens (the sequential-generate row shape,
        minus its trailing pads)."""
        return np.concatenate(
            [self.prompt_ids, np.asarray(self.token_ids, np.int32)])


def _prefill_traced(params, ids, true_len, ck, cv, slot, cos, sin, *,
                    args, metrics):
    # runs once per COMPILE (trace time), not per call — see metrics.py
    metrics.inc("prefill_compiles")
    L = ck.shape[0]
    sck = jnp.zeros((L, 1) + ck.shape[2:], ck.dtype)
    scv = jnp.zeros_like(sck)
    logits, sck, scv = gen._forward_cached(
        params, ids, sck, scv, 0, cos, sin, args, last_idx=true_len - 1)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]
    ck = jax.lax.dynamic_update_slice_in_dim(ck, sck, slot, axis=1)
    cv = jax.lax.dynamic_update_slice_in_dim(cv, scv, slot, axis=1)
    return ck, cv, first


def _decode_traced(params, tokens, ck, cv, pos, cos, sin, *, args, metrics):
    metrics.inc("decode_compiles")
    logits, ck, cv = gen._forward_cached(
        params, tokens[:, None], ck, cv, pos, cos, sin, args)
    return ck, cv, jnp.argmax(logits, axis=-1).astype(jnp.int32)


class Engine:
    """Continuous-batching serving engine over a Llama functional param
    tree (float or `quantize_params` int8).

    max_slots: S — concurrent in-flight requests (the decode batch).
    max_len:   per-slot KV capacity; prompt_len + max_new_tokens must stay
               within it. On TPU pick a multiple of 128 so the Pallas
               decode-attention fast path stays eligible.
    min_bucket: smallest prefill length bucket (power-of-two ladder up to
               max_len).
    """

    def __init__(self, params, args, *, max_slots=4, max_len=256,
                 min_bucket=16, pad_id=0, metrics=None):
        self.params = params
        self.args = args
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.min_bucket = int(min_bucket)
        self.pad_id = int(pad_id)
        self.metrics = metrics if metrics is not None else Metrics()

        self.queue = AdmissionQueue(self.metrics)
        self.slots = SlotTable(self.max_slots)
        self._npos = np.zeros(self.max_slots, np.int32)   # next write pos
        self._last_tok = np.full(self.max_slots, self.pad_id, np.int32)
        self.step_count = 0
        self._setup_device_state()

    def _setup_device_state(self):
        """Allocate the KV cache buffers + compile wrappers (subclass
        hook: the paged engine replaces the per-slot stripes with a page
        pool here)."""
        args = self.args
        L = lf.stack_leading_dim(self.params["layers"])
        hd = args.hidden_size // args.num_heads
        cache_dtype = self.params["embedding"].dtype
        self._ck = jnp.zeros(
            (L, self.max_slots, args.num_kv_heads, self.max_len, hd),
            cache_dtype)
        self._cv = jnp.zeros_like(self._ck)
        self._cos, self._sin = lf.rope_tables(self.max_len, hd,
                                              args.rope_theta)

        # donate the KV cache buffers: the engine threads ck/cv through
        # every step and immediately drops the old arrays, so XLA aliases
        # input to output instead of materializing a fresh cache copy per
        # step (on the TPU bench shape that copy is ~1 GB/step). CPU/other
        # backends don't implement donation — skip it there to avoid a
        # warning per compile.
        donate = jax.default_backend() == "tpu"
        self._prefill = jax.jit(
            functools.partial(_prefill_traced, args=args,
                              metrics=self.metrics),
            donate_argnums=(3, 4) if donate else ())
        self._decode = jax.jit(
            functools.partial(_decode_traced, args=args,
                              metrics=self.metrics),
            donate_argnums=(2, 3) if donate else ())

    # -- admission ----------------------------------------------------------
    def submit(self, req):
        """Queue a Request (or raw prompt ids). Returns the Request."""
        if not isinstance(req, Request):
            req = Request(req)
        n = int(req.prompt_ids.size)
        bucket_for(n, self.min_bucket, self.max_len)  # length must fit
        if n + req.max_new_tokens > self.max_len + 1:
            raise ValueError(
                f"request needs {n} prompt + {req.max_new_tokens} new "
                f"tokens but the slot capacity is max_len={self.max_len}")
        req.submit_time = time.perf_counter()
        req.submit_step = self.step_count
        self.queue.push(req)
        self.metrics.inc("requests_submitted")
        return req

    # -- the iteration-level scheduler --------------------------------------
    def step(self):
        """One engine iteration: admit-and-prefill if a request is waiting
        and a slot is free (paged engines also require page capacity),
        else one batched decode step over all active slots, else idle.
        Returns a small event dict."""
        if self._can_prefill():
            ev = self._prefill_step()
        elif self.slots.active_slots:
            ev = self._decode_step()
        else:
            ev = {"type": "idle"}
        self.step_count += 1
        self.metrics.observe("slot_occupancy", self.slots.occupancy())
        self.metrics.set_gauge("active_slots", len(self.slots.active_slots))
        return ev

    def _can_prefill(self):
        """True when the next queued request can be admitted this step
        (subclass hook: the paged engine also checks page-pool capacity
        for the queue head)."""
        return bool(self.queue and self.slots.free_count)

    def run_until_idle(self):
        """Drive step() until every queued/active request completes."""
        while self.queue or self.slots.active_slots:
            self.step()

    def serve(self, requests):
        """Convenience: submit all, run to completion, return them."""
        reqs = [self.submit(r) for r in requests]
        self.run_until_idle()
        return reqs

    def replay(self, trace):
        """Replay an arrival trace (tools/serving_trace.py): each entry
        {'arrival_step', 'prompt', 'max_new_tokens'[, 'eos_token_id']} is
        submitted once the engine reaches its arrival step; idle steps
        advance virtual time between sparse arrivals. Returns Requests in
        trace order."""
        pending = sorted(trace, key=lambda t: t["arrival_step"])
        out = {}
        i = 0
        while i < len(pending) or self.queue or self.slots.active_slots:
            while (i < len(pending)
                   and pending[i]["arrival_step"] <= self.step_count):
                t = pending[i]
                req = Request(t["prompt"], t["max_new_tokens"],
                              eos_token_id=t.get("eos_token_id"),
                              request_id=t.get("request_id"))
                out[id(t)] = self.submit(req)
                i += 1
            self.step()
        return [out[id(t)] for t in trace]

    def reset(self):
        """Forget all requests/slots (keeps compiled programs AND compile
        counters; per-run metrics are cleared) — benchmark warmup then
        timed replay on one engine without recompiling."""
        if self.queue or self.slots.active_slots:
            raise RuntimeError("reset() with requests still in flight")
        self.metrics.reset(keep_counters=("prefill_compiles",
                                          "decode_compiles"))
        self.queue = AdmissionQueue(self.metrics)
        self.slots = SlotTable(self.max_slots)
        self._npos[:] = 0
        self._last_tok[:] = self.pad_id
        self.step_count = 0

    # -- internals ----------------------------------------------------------
    def _prefill_step(self):
        req = self.queue.pop()
        slot = self.slots.admit(req)
        n = int(req.prompt_ids.size)
        bucket, first = self._prefill_device(req, slot, n)
        now = time.perf_counter()
        req.first_token_time = now
        # TTFT in wall-clock seconds AND in engine steps: steps are the
        # load-independent scheduling-delay unit arrival traces are written
        # in; seconds are what ROADMAP 2's p99 acceptance is measured in
        req.ttft_s = now - req.submit_time
        req.ttft_steps = self.step_count - req.submit_step
        self.metrics.observe("ttft_s", req.ttft_s)
        self.metrics.observe("ttft_steps", req.ttft_steps)
        self.metrics.inc("prefills")
        self.metrics.inc("tokens_generated")
        self._npos[slot] = n
        self._last_tok[slot] = first
        self._emit(req, first)
        if req.finished:
            self._retire(slot)
        return {"type": "prefill", "request_id": req.request_id,
                "slot": slot, "bucket": bucket, "token": first}

    def _prefill_device(self, req, slot, n):
        """Run the device half of a prefill (subclass hook). Returns
        (bucket, first_token)."""
        bucket = bucket_for(n, self.min_bucket, self.max_len)
        padded = np.full((1, bucket), self.pad_id, np.int32)
        padded[0, :n] = req.prompt_ids
        with self.metrics.timer("prefill_s"):
            self._ck, self._cv, first = self._prefill(
                self.params, jnp.asarray(padded), jnp.int32(n),
                self._ck, self._cv, jnp.int32(slot), self._cos, self._sin)
            first = int(first)
        return bucket, first

    def _decode_step(self):
        active = self.slots.active_slots
        nxt = self._decode_device(active)
        emitted = {}
        for slot in active:
            self._npos[slot] += 1
            tok = int(nxt[slot])
            self._last_tok[slot] = tok
            req = self.slots.owner(slot)
            self._emit(req, tok)
            emitted[req.request_id] = tok
            if req.finished:
                self._retire(slot)
        self.metrics.inc("decode_steps")
        self.metrics.inc("tokens_generated", len(active))
        self.metrics.observe("tokens_per_decode_step", len(active))
        return {"type": "decode", "tokens": emitted}

    def _decode_device(self, active):
        """Run the device half of one batched decode step (subclass
        hook). Returns the next-token array [S] on host."""
        with self.metrics.timer("decode_step_s"):
            self._ck, self._cv, nxt = self._decode(
                self.params, jnp.asarray(self._last_tok), self._ck,
                self._cv, jnp.asarray(self._npos), self._cos, self._sin)
        return np.asarray(nxt)

    def _emit(self, req, token):
        req.token_ids.append(token)
        finished, reason = False, None
        if req.eos_token_id is not None and token == req.eos_token_id:
            finished, reason = True, "eos"
        elif len(req.token_ids) >= req.max_new_tokens:
            finished, reason = True, "length"
        if req.stream_cb is not None:
            req.stream_cb(req, token, finished)
        if finished:
            req.finished = True
            req.finish_reason = reason
            req.finish_time = time.perf_counter()
            self.metrics.inc("requests_finished")

    def _retire(self, slot):
        self.slots.retire(slot)
        self._npos[slot] = 0
        self._last_tok[slot] = self.pad_id
