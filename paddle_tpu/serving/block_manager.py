"""Page-pool allocator + radix-tree prefix cache for the paged KV cache.

The paged serving engine (`serving/paged_engine.py`) replaces per-slot
`max_len` KV stripes with a fixed pool of PAGES
(`[L, num_pages, nkv, page_size, hd]`) and a per-slot block table — the
vLLM PagedAttention (Kwon et al., SOSP'23) memory model. This module is
the host-side brain of that cache; nothing here touches device arrays:

  - `BlockAllocator` hands out page ids from a free list with REFCOUNTS,
    so one physical page can back many slots (a shared system prompt is
    resident once);
  - the PREFIX CACHE is a RADIX TREE over token sequences
    (RadixAttention, Zheng et al. 2023): `match_prefix` returns the
    longest cached prefix at TOKEN granularity — whole shared pages plus
    one PARTIAL page when two prompts diverge mid-page. Each tree node
    stores the token edge from its parent and owns the pages it
    introduced; a mid-edge divergence SPLITS the node, and the
    straddling page is shared copy-on-write (the engine gathers the
    cached half out of the frozen page and scatters into a fresh copy,
    so both children keep reading the ancestor's bytes). The exact-match
    hash chain this replaces survives as `policy="hash"` — the bench
    baseline the radix hit-rate is measured against;
  - pages whose refcount drops to zero but that remain tree-registered
    become EVICTABLE instead of free: they keep their contents and can
    be revived by a later prefix hit, or reclaimed under pressure by
    LEAF-LRU eviction — only the trailing page of a least-recently-hit
    LEAF is ever taken, so hot interior prefixes (the shared system
    prompt) survive while cold divergent tails are peeled off from the
    outside in;
  - `ensure_writable` is the COPY-ON-WRITE gate: writing into a page
    that is shared (refcount > 1) or tree-registered would corrupt the
    other readers, so the writer gets a fresh page and the caller copies
    the device contents across.

Page id 0 is the NULL page: never allocated, a garbage sink for inactive
block-table rows and a safe gather target for unused entries (the
position mask keeps it unread on every real path).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, NamedTuple, Optional

__all__ = ["BlockAllocator", "PrefixMatch", "NULL_PAGE"]

NULL_PAGE = 0


class PrefixMatch(NamedTuple):
    """Result of a longest-prefix scan over the cache.

    pages:        full cached pages; pages[i] holds tokens
                  [i*page_size, (i+1)*page_size) of the query.
    partial_page: page whose leading `partial_len` positions hold tokens
                  [len(pages)*page_size, matched) — the mid-page share a
                  radix split exposes. None under the hash policy and on
                  page-aligned matches. The page is FROZEN: the engine
                  must gather from it and write into its own copy.
    partial_len:  valid leading tokens on partial_page (0 when None).
    matched:      total cached tokens = len(pages)*page_size+partial_len.
    """

    pages: List[int]
    partial_page: Optional[int]
    partial_len: int
    matched: int


_EMPTY_MATCH = PrefixMatch([], None, 0, 0)


class _RadixNode:
    """One radix-tree node. `edge` is the token run from the parent;
    `start` its absolute offset in any sequence through this node. The
    node OWNS the pages it introduced: page indices
    [start//ps, end//ps - 1] when it has children (the straddling end
    page, if any, belongs to the children's COW copies), and
    [start//ps, (end-1)//ps] when it is a leaf (the trailing partial
    page is frozen here). A node whose edge starts and ends inside the
    same page owns nothing — its boundary copy lives with whichever
    child extends it. Every owned page id appears exactly once in the
    whole tree."""

    __slots__ = ("edge", "start", "pages", "children", "parent", "stamp")

    def __init__(self, edge, start, pages, parent):
        self.edge = edge          # tuple of ints
        self.start = start        # absolute token offset of edge[0]
        self.pages = pages        # owned page ids, path order
        self.children = {}        # first edge token -> _RadixNode
        self.parent = parent
        self.stamp = 0            # LRU clock of the last committed hit

    @property
    def end(self):
        return self.start + len(self.edge)


class _RadixIndex:
    """Token-granular radix prefix index (policy="radix")."""

    def __init__(self, alloc):
        self._a = alloc
        self.root = _RadixNode((), 0, [], None)
        self._owner = {}          # page id -> owning node
        self._clock = 0

    def _tick(self):
        self._clock += 1
        return self._clock

    def owns(self, page):
        return page in self._owner

    # -- longest-prefix match ----------------------------------------------
    def match(self, tokens, touch=False):
        """Longest cached prefix of `tokens`, capped at len-1 so the
        final token is always recomputed (its next-token logits are the
        point of the prefill). Pure tree walk — refcounts are the
        allocator's business."""
        ps = self._a.page_size
        limit = len(tokens) - 1
        if limit <= 0:
            return _EMPTY_MATCH
        toks = [int(t) for t in tokens[:limit]]
        acc = []                  # pages in path order: acc[i] covers page i
        node = self.root
        path = [node]
        m = 0
        while m < limit:
            child = node.children.get(toks[m])
            if child is None:
                break
            edge, k = child.edge, 0
            while k < len(edge) and m + k < limit and edge[k] == toks[m + k]:
                k += 1
            acc.extend(child.pages)
            path.append(child)
            m += k
            if k < len(edge):
                break
            node = child
        if touch:
            t = self._tick()
            for nd in path:
                nd.stamp = t
        full, plen = m // ps, m % ps
        partial = None
        if plen:
            partial = self._page_covering(path[-1], acc, full)
            if partial is None:     # defensive: degrade to page-aligned
                plen, m = 0, full * ps
        return PrefixMatch(acc[:full], partial, plen, m)

    def _page_covering(self, last, acc, idx):
        """Physical page holding page-index `idx` of the matched path.
        Usually already in `acc`; when the walk ended at a node whose
        edge straddles into a page owned by its children, descend — any
        branch works, every descendant shares the path's tokens through
        at least the walk's end."""
        if idx < len(acc):
            return acc[idx]
        node = last
        while node.children:
            node = next(iter(node.children.values()))
            first = node.start // self._a.page_size
            if first <= idx < first + len(node.pages):
                return node.pages[idx - first]
        return None

    # -- registration -------------------------------------------------------
    def register(self, tokens, pages):
        """Insert `tokens` (backed by `pages`, page i holding tokens
        [i*ps, (i+1)*ps), the last page possibly partial) into the tree.
        Walks existing edges, splits at a mid-edge divergence, and hangs
        one new leaf owning the pages past the divergence. Pages already
        owned elsewhere are never re-claimed (the walk passes through
        them); registration never touches refcounts."""
        ps = self._a.page_size
        toks = [int(t) for t in tokens]
        n = len(toks)
        if not n or not pages:
            return
        if (len(pages) - 1) * ps >= n:
            raise ValueError("register_prefix: more pages than the token "
                             "prefix covers")
        pages = list(pages)
        node, i = self.root, 0
        while i < n:
            child = node.children.get(toks[i])
            if child is None:
                self._insert_leaf(node, toks, i, pages)
                return
            edge, k = child.edge, 0
            while k < len(edge) and i + k < n and edge[k] == toks[i + k]:
                k += 1
            if k == len(edge):
                node = child
                i += k
                continue
            if i + k == n:
                return          # strict prefix of an existing edge
            mid = self._split(child, k)
            self._insert_leaf(mid, toks, i + k, pages)
            return
        # walked the whole sequence along existing edges: already cached

    def _insert_leaf(self, parent, toks, i, pages):
        """Hang a new leaf for tokens [i, n) under `parent`. The leaf
        owns pages from index i//ps on — including the caller's COW copy
        of a straddled boundary page. Any candidate page already owned
        elsewhere (the tree moved between match and register) truncates
        the claim at the preceding page boundary."""
        ps = self._a.page_size
        n = len(toks)
        first = i // ps
        sel = []
        for idx in range(first, len(pages)):
            p = pages[idx]
            if p == NULL_PAGE or p in self._owner:
                break
            sel.append(p)
        if not sel:
            return
        end = min(n, (first + len(sel)) * ps)
        if end <= i:
            return
        leaf = _RadixNode(tuple(toks[i:end]), i, sel, parent)
        parent.children[toks[i]] = leaf
        for p in sel:
            self._owner[p] = leaf
        leaf.stamp = self._tick()
        self._a.prefix_version += 1

    def _split(self, child, k):
        """Split `child` at edge offset k: a new interior node keeps
        edge[:k] and the whole pages before the split point; `child` is
        demoted under it keeping the rest — including its copy of the
        straddled boundary page, which the new sibling will mirror with
        a COW copy of its own."""
        parent = child.parent
        d = child.start + k
        ps = self._a.page_size
        keep = d // ps - child.start // ps      # whole pages before d
        mid = _RadixNode(child.edge[:k], child.start, child.pages[:keep],
                         parent)
        parent.children[mid.edge[0]] = mid
        child.edge = child.edge[k:]
        child.start = d
        child.pages = child.pages[keep:]
        child.parent = mid
        mid.children = {child.edge[0]: child}
        mid.stamp = child.stamp
        for p in mid.pages:
            self._owner[p] = mid
        if self._a._metrics is not None:
            self._a._metrics.inc("radix_splits")
        self._a.prefix_version += 1
        return mid

    # -- eviction -----------------------------------------------------------
    def evict_one(self):
        """Reclaim ONE page by leaf-LRU: among leaves whose trailing
        page is refcount-0 (cached), take the least recently hit and
        peel its last page. Interior pages — the shared hot prefix — are
        structurally untouchable until their subtree has been consumed
        leaf by leaf. Returns the page id, or None when nothing is
        evictable."""
        cached = self._a._cached
        best = None
        stack = [self.root]
        while stack:
            nd = stack.pop()
            stack.extend(nd.children.values())
            if nd.children or nd is self.root or not nd.pages:
                continue
            if nd.pages[-1] not in cached:
                continue
            if best is None or nd.stamp < best.stamp:
                best = nd
        if best is None:
            return None
        p = best.pages.pop()
        del cached[p]
        del self._owner[p]
        self._a.prefix_version += 1
        ps = self._a.page_size
        if best.pages:
            new_end = (best.start // ps + len(best.pages)) * ps
            best.edge = best.edge[:new_end - best.start]
        else:
            self._remove(best)
        return p

    def _remove(self, node):
        """Unlink a page-less leaf, cascading through interior nodes
        that held no pages of their own and just lost their last
        child."""
        while node is not self.root:
            parent = node.parent
            del parent.children[node.edge[0]]
            if parent.children or parent.pages or parent is self.root:
                return
            node = parent

    # -- accounting ---------------------------------------------------------
    def reclaimable(self):
        """Pages alloc() could obtain by repeated leaf-LRU eviction: the
        trailing run of cached pages of every node whose whole subtree
        is evictable (an interior page only frees up once everything
        hanging off it is gone). Iterative post-order — tree depth grows
        with registrations, not page counts."""
        cached = self._a._cached
        order, stack = [], [self.root]
        while stack:
            nd = stack.pop()
            order.append(nd)
            stack.extend(nd.children.values())
        res = {}
        for nd in reversed(order):
            total, fully = 0, True
            for c in nd.children.values():
                t, f = res[id(c)]
                total += t
                fully = fully and f
            if fully:
                tail = 0
                for p in reversed(nd.pages):
                    if p in cached:
                        tail += 1
                    else:
                        break
                total += tail
                fully = tail == len(nd.pages)
            res[id(nd)] = (total, fully)
        return res[id(self.root)][0]


class _HashChainIndex:
    """The PR-8 exact-match chain, kept verbatim as `policy="hash"`: a
    table keyed on `(parent_page_id, page_of_token_ids)` shares only
    FULL pages on a strict chain, and eviction is insertion-order LRU
    with descendant orphaning. It is the baseline the radix policy's
    hit-rate gain is benchmarked against."""

    def __init__(self, alloc):
        self._a = alloc
        self._table = {}          # (parent | -1, tokens tuple) -> page
        self._key_of = {}         # registered page -> its table key
        self._parent = {}         # registered page -> parent page (or -1)
        self._children = {}       # page -> set of registered child pages

    def _chunk(self, tokens, i):
        ps = self._a.page_size
        return tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])

    def owns(self, page):
        return page in self._key_of

    def match(self, tokens, touch=False):
        ps = self._a.page_size
        max_pages = (len(tokens) - 1) // ps
        pages, parent = [], -1
        for i in range(max_pages):
            p = self._table.get((parent, self._chunk(tokens, i)))
            if p is None:
                break
            pages.append(p)
            parent = p
        return PrefixMatch(pages, None, 0, len(pages) * ps)

    def register(self, tokens, pages):
        ps = self._a.page_size
        if (len(pages) - 1) * ps >= len(tokens):
            raise ValueError("register_prefix: more pages than the token "
                             "prefix covers")
        pages = pages[:len(tokens) // ps]   # full pages only
        parent = -1
        for i, p in enumerate(pages):
            key = (parent, self._chunk(tokens, i))
            existing = self._table.get(key)
            if existing is not None:
                parent = existing
                continue
            if p in self._key_of:   # already registered under another chain
                parent = p
                continue
            self._table[key] = p
            self._key_of[p] = key
            self._parent[p] = parent
            if parent != -1:
                self._children.setdefault(parent, set()).add(p)
            parent = p
            self._a.prefix_version += 1

    def reclaimable(self):
        return len(self._a._cached)

    def evict_one(self):
        cached = self._a._cached
        if not cached:
            return None
        p = next(iter(cached))              # least recently used
        del cached[p]
        self._unregister(p)
        return p

    def _unregister(self, page):
        """Remove a page's hash registration and ORPHAN its descendants:
        their chain keys embed this page's id, which a recycled page
        could spoof into serving stale contents. Orphaned cached
        descendants become plain free pages; orphaned in-use descendants
        just lose future hits."""
        key = self._key_of.pop(page, None)
        if key is None:
            return
        self._a.prefix_version += 1
        self._table.pop(key, None)
        parent = self._parent.pop(page, None)
        if parent is not None and parent != -1:
            self._children.get(parent, set()).discard(page)
        for child in list(self._children.pop(page, ())):
            self._unregister(child)
            if child in self._a._cached:
                del self._a._cached[child]
                self._a._free.append(child)


class BlockAllocator:
    """Host-side page allocator with refcounts, prefix reuse, eviction
    of cached pages, and copy-on-write. Single-threaded — called only
    from the engine's scheduler loop between device steps.

    policy="radix" (default) indexes prefixes in a token-granular radix
    tree with COW page splits and leaf-LRU eviction; policy="hash"
    keeps the PR-8 exact-match full-page chain as a baseline."""

    def __init__(self, num_pages, page_size, metrics=None, policy="radix"):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the null page)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.policy = str(policy)
        self._metrics = metrics
        self._free = list(range(self.num_pages - 1, 0, -1))  # pop -> lowest
        self._ref = {}              # page -> refcount (>= 1)
        self._cached = OrderedDict()  # refcount-0 registered pages, LRU order
        # bumped on every prefix-index mutation (registration, split,
        # eviction) — lets callers memoize side-effect-free match_prefix
        # scans (the chunked-prefill anti-convoy admission walk) until a
        # change could alter the answer
        self.prefix_version = 0
        if self.policy == "radix":
            self._index = _RadixIndex(self)
        elif self.policy == "hash":
            self._index = _HashChainIndex(self)
        else:
            raise ValueError(f"unknown prefix policy {policy!r} "
                             "(expected 'radix' or 'hash')")
        self._gauges()

    # -- introspection ------------------------------------------------------
    @property
    def capacity(self):
        """Allocatable pages (the null page excluded)."""
        return self.num_pages - 1

    @property
    def free_count(self):
        return len(self._free)

    @property
    def available(self):
        """Pages an alloc() can obtain: free + evictable cached. Under
        the radix policy an interior cached page only counts once its
        whole subtree is evictable (leaf-LRU can't reach it before)."""
        return len(self._free) + self._index.reclaimable()

    @property
    def pages_in_use(self):
        return len(self._ref)

    def refcount(self, page):
        return self._ref.get(page, 0)

    def is_registered(self, page):
        return self._index.owns(page)

    def _gauges(self):
        if self._metrics is not None:
            self._metrics.set_gauge("pages_in_use", len(self._ref))
            self._metrics.set_gauge("pages_free", self.available)

    # -- alloc / ref / release ---------------------------------------------
    def alloc(self):
        """Take an exclusive page (refcount 1): from the free list, else
        by evicting per the policy (leaf-LRU for radix, insertion-order
        LRU for hash). Raises when the pool is exhausted."""
        if self._free:
            p = self._free.pop()
        else:
            p = self._index.evict_one()
            if p is None:
                raise RuntimeError(
                    f"KV page pool exhausted ({self.capacity} pages, "
                    f"{len(self._ref)} in use) — admission should have "
                    f"gated this request")
            if self._metrics is not None:
                self._metrics.inc("page_evictions")
        self._ref[p] = 1
        self._gauges()
        return p

    def ref(self, page):
        """Add a reader. Reviving a cached (refcount-0) page pulls it
        off the eviction list but keeps its tree registration — the
        prefix-hit path."""
        if page == NULL_PAGE:
            raise ValueError("cannot ref the null page")
        if page in self._ref:
            self._ref[page] += 1
        elif page in self._cached:
            del self._cached[page]
            self._ref[page] = 1
        else:
            raise KeyError(f"ref of unallocated page {page}")
        self._gauges()

    def release(self, page):
        """Drop a reader. At refcount 0 a tree-registered page becomes
        evictable (contents kept for future prefix hits, most recent at
        the back of the LRU); an unregistered page returns to the free
        list."""
        if page == NULL_PAGE:
            return
        r = self._ref[page] - 1
        if r > 0:
            self._ref[page] = r
            return
        del self._ref[page]
        if self._index.owns(page):
            self._cached[page] = True       # most-recently-used position
        else:
            self._free.append(page)
        self._gauges()

    # -- copy-on-write ------------------------------------------------------
    def ensure_writable(self, page):
        """COW gate before writing into `page`. An exclusive,
        unregistered page comes back unchanged (the overwhelmingly
        common case — a slot's partially-filled tail page). A shared or
        tree-registered page is swapped for a freshly allocated one:
        returns (new_page, True) and the caller must copy the device
        contents old -> new before writing."""
        if page != NULL_PAGE and self._ref.get(page, 0) == 1 \
                and not self._index.owns(page):
            return page, False
        new = self.alloc()
        self.release(page)
        if self._metrics is not None:
            self._metrics.inc("cow_copies")
        self._gauges()
        return new, True

    # -- prefix cache -------------------------------------------------------
    def match_prefix(self, tokens, commit=True):
        """Longest cached prefix of `tokens` as a PrefixMatch — full
        pages plus (radix only) one frozen partial page — capped at
        len-1 tokens so at least the final token is always recomputed.
        With commit=True every hit page INCLUDING the partial is ref'd
        for the caller (reviving cached pages) and the path's LRU stamp
        is bumped; commit=False is a side-effect-free peek for admission
        checks."""
        m = self._index.match(tokens, touch=commit)
        if commit:
            for p in m.pages:
                self.ref(p)
            if m.partial_page is not None:
                self.ref(m.partial_page)
                if self._metrics is not None:
                    self._metrics.inc("prefix_partial_hits")
        return m

    def register_prefix(self, tokens, pages):
        """Make `pages` (the block-table prefix; page i holds tokens
        [i*ps, (i+1)*ps), the last possibly partial) hittable for future
        prompts. The radix policy keeps the partial tail page (frozen —
        the owner must COW before writing past it); the hash policy
        trims to full pages. Pages already indexed (this prompt's own
        hits) are walked through, not re-claimed."""
        self._index.register(tokens, pages)
