"""Page-pool allocator + hash-chained prefix cache for the paged KV cache.

The paged serving engine (`serving/paged_engine.py`) replaces per-slot
`max_len` KV stripes with a fixed pool of PAGES
(`[L, num_pages, nkv, page_size, hd]`) and a per-slot block table — the
vLLM PagedAttention (Kwon et al., SOSP'23) memory model. This module is
the host-side brain of that cache; nothing here touches device arrays:

  - `BlockAllocator` hands out page ids from a free list with REFCOUNTS,
    so one physical page can back many slots (a shared system prompt is
    resident once);
  - the PREFIX CACHE is a hash-chained table keyed on
    `(parent_page_id, page_of_token_ids)` — exact-match chaining (the
    dict compares the actual token tuples, so there are no hash-collision
    false hits, the failure mode RadixAttention-style token hashing has
    to re-verify against). Walking the chain from the root yields the
    longest cached full-page prefix of a new prompt;
  - pages whose refcount drops to zero but that remain hash-registered
    become EVICTABLE instead of free: they keep their contents and can be
    revived by a later prefix hit, or reclaimed in LRU order when the
    free list runs dry. Evicting a page orphans its hash descendants
    (their chain key embeds the evicted page's id, which a recycled page
    would otherwise spoof into serving stale contents);
  - `ensure_writable` is the COPY-ON-WRITE gate: writing into a page that
    is shared (refcount > 1) or hash-registered would corrupt the other
    readers, so the writer gets a fresh page and the caller copies the
    device contents across.

Page id 0 is the NULL page: never allocated, a garbage sink for inactive
block-table rows and a safe gather target for unused entries (the
position mask keeps it unread on every real path).
"""

from __future__ import annotations

from collections import OrderedDict

__all__ = ["BlockAllocator", "NULL_PAGE"]

NULL_PAGE = 0


class BlockAllocator:
    """Host-side page allocator with refcounts, prefix-hash reuse, LRU
    eviction of cached pages, and copy-on-write. Single-threaded — called
    only from the engine's scheduler loop between device steps."""

    def __init__(self, num_pages, page_size, metrics=None):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the null page)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._metrics = metrics
        self._free = list(range(self.num_pages - 1, 0, -1))  # pop -> lowest
        self._ref = {}              # page -> refcount (>= 1)
        self._cached = OrderedDict()  # refcount-0 registered pages, LRU order
        self._table = {}            # (parent_page | -1, tokens tuple) -> page
        self._key_of = {}           # registered page -> its table key
        self._parent = {}           # registered page -> parent page (or -1)
        self._children = {}         # page -> set of registered child pages
        # bumped whenever the prefix table changes — lets callers memoize
        # side-effect-free match_prefix scans (the chunked-prefill
        # anti-convoy admission walk) until a registration or eviction
        # could change the answer
        self.prefix_version = 0
        self._gauges()

    # -- introspection ------------------------------------------------------
    @property
    def capacity(self):
        """Allocatable pages (the null page excluded)."""
        return self.num_pages - 1

    @property
    def free_count(self):
        return len(self._free)

    @property
    def available(self):
        """Pages an alloc() can obtain: free + evictable cached."""
        return len(self._free) + len(self._cached)

    @property
    def pages_in_use(self):
        return len(self._ref)

    def refcount(self, page):
        return self._ref.get(page, 0)

    def is_registered(self, page):
        return page in self._key_of

    def _gauges(self):
        if self._metrics is not None:
            self._metrics.set_gauge("pages_in_use", len(self._ref))
            self._metrics.set_gauge("pages_free", self.available)

    # -- alloc / ref / release ---------------------------------------------
    def alloc(self):
        """Take an exclusive page (refcount 1): from the free list, else by
        evicting the least-recently-used cached page. Raises when the pool
        is exhausted."""
        if self._free:
            p = self._free.pop()
        elif self._cached:
            p = self._evict_lru()
        else:
            raise RuntimeError(
                f"KV page pool exhausted ({self.capacity} pages, "
                f"{len(self._ref)} in use) — admission should have gated "
                f"this request")
        self._ref[p] = 1
        self._gauges()
        return p

    def ref(self, page):
        """Add a reader. Reviving a cached (refcount-0) page pulls it off
        the eviction list but keeps its hash registration — the prefix-hit
        path."""
        if page == NULL_PAGE:
            raise ValueError("cannot ref the null page")
        if page in self._ref:
            self._ref[page] += 1
        elif page in self._cached:
            del self._cached[page]
            self._ref[page] = 1
        else:
            raise KeyError(f"ref of unallocated page {page}")
        self._gauges()

    def release(self, page):
        """Drop a reader. At refcount 0 a hash-registered page becomes
        evictable (contents kept for future prefix hits, most recent at the
        back of the LRU); an unregistered page returns to the free list."""
        if page == NULL_PAGE:
            return
        r = self._ref[page] - 1
        if r > 0:
            self._ref[page] = r
            return
        del self._ref[page]
        if page in self._key_of:
            self._cached[page] = True       # most-recently-used position
        else:
            self._free.append(page)
        self._gauges()

    # -- copy-on-write ------------------------------------------------------
    def ensure_writable(self, page):
        """COW gate before writing into `page`. An exclusive, unregistered
        page comes back unchanged (the overwhelmingly common case — a
        slot's partially-filled tail page). A shared or hash-registered
        page is swapped for a freshly allocated one: returns
        (new_page, True) and the caller must copy the device contents
        old -> new before writing."""
        if page != NULL_PAGE and self._ref.get(page, 0) == 1 \
                and page not in self._key_of:
            return page, False
        new = self.alloc()
        self.release(page)
        if self._metrics is not None:
            self._metrics.inc("cow_copies")
        self._gauges()
        return new, True

    # -- prefix cache -------------------------------------------------------
    def _chunk(self, tokens, i):
        ps = self.page_size
        return tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])

    def match_prefix(self, tokens, commit=True):
        """Longest chain of cached FULL pages covering a STRICT prefix of
        `tokens` — capped at (len-1)//page_size pages so at least the final
        token is always recomputed (its next-token logits are the point of
        the prefill). With commit=True every hit page is ref'd for the
        caller (reviving cached pages); commit=False is a side-effect-free
        peek for admission checks."""
        max_pages = (len(tokens) - 1) // self.page_size
        pages, parent = [], -1
        for i in range(max_pages):
            p = self._table.get((parent, self._chunk(tokens, i)))
            if p is None:
                break
            pages.append(p)
            parent = p
        if commit:
            for p in pages:
                self.ref(p)
        return pages

    def register_prefix(self, tokens, pages):
        """Register `pages` (the block-table prefix; page i holds tokens
        [i*ps, (i+1)*ps)) in the hash chain so future prompts sharing this
        prefix hit them. Only pages FULLY covered by `tokens` may be
        passed. Pages already on the chain (this prompt's own hits) are
        walked through, not re-registered."""
        if len(pages) * self.page_size > len(tokens):
            raise ValueError("register_prefix: pages not fully covered by "
                             "the token prefix")
        parent = -1
        for i, p in enumerate(pages):
            key = (parent, self._chunk(tokens, i))
            existing = self._table.get(key)
            if existing is not None:
                parent = existing
                continue
            if p in self._key_of:   # already registered under another chain
                parent = p
                continue
            self._table[key] = p
            self._key_of[p] = key
            self._parent[p] = parent
            if parent != -1:
                self._children.setdefault(parent, set()).add(p)
            parent = p
            self.prefix_version += 1

    # -- eviction -----------------------------------------------------------
    def _evict_lru(self):
        p = next(iter(self._cached))        # least recently used
        del self._cached[p]
        self._unregister(p)
        if self._metrics is not None:
            self._metrics.inc("page_evictions")
        return p

    def _unregister(self, page):
        """Remove a page's hash registration and ORPHAN its descendants:
        their chain keys embed this page's id, which a recycled page could
        spoof into serving stale contents. Orphaned cached descendants
        become plain free pages; orphaned in-use descendants just lose
        future hits."""
        key = self._key_of.pop(page, None)
        if key is None:
            return
        self.prefix_version += 1
        self._table.pop(key, None)
        parent = self._parent.pop(page, None)
        if parent is not None and parent != -1:
            self._children.get(parent, set()).discard(page)
        for child in list(self._children.pop(page, ())):
            self._unregister(child)
            if child in self._cached:
                del self._cached[child]
                self._free.append(child)
