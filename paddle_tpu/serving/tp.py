"""Tensor-parallel placement for the serving engines.

Serving a model bigger than one chip's HBM means sharding the weights
AND the KV cache over a mesh `mp` axis and running every engine step as
one SPMD program. The placement is the Megatron inference split the
training side already uses (`llama_functional.decoder_layer`,
SNIPPETS [1]/[3] NamedSharding shape):

  - column-parallel: wq/wk/wv/w_gate/w_up sharded on the OUT dim — each
    device owns num_heads/mp query heads, num_kv_heads/mp kv heads and
    intermediate/mp FFN channels;
  - row-parallel: wo/w_down sharded on the IN dim, outputs psum-reduced
    (`generation._tp_reduce`) so the residual stream stays replicated;
  - the PAGED KV POOL `[L, num_pages, nkv, page_size, hd]` shards on the
    nkv axis: a page id means the same thing on every device, so BLOCK
    TABLES STAY REPLICATED — the host-side BlockAllocator (refcounts,
    prefix hash, COW, eviction) is completely sharding-oblivious;
  - embedding / norms / lm_head replicated (tiny next to the layer
    stack; vocab-parallel lm_head would force a cross-device argmax into
    the sampler for marginal bytes).

Weight-only int8 trees shard the same way: a QuantizedWeight's `q`
follows its weight and the per-out-channel `scale` follows the out dim
(replicated for row-parallel shards, whose out dim is unsplit).

Params are placed EAGERLY (`shard_params` -> jax.device_put with
NamedSharding) at engine construction, and the engine's traced step
bodies run under `mesh_utils.shard_map_compat` — the jax-0.4.37-safe
spelling — with these specs as in_specs/out_specs. Everything here is
data (PartitionSpec trees); the collectives live in models/generation.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.models.generation import QuantizedWeight

__all__ = ["tp_validate", "llama_tp_specs", "pool_spec", "shard_params"]

# column-parallel leaves: sharded on the out (last) dim; row-parallel:
# sharded on the in dim with a psum epilogue
_COL = ("wq", "wk", "wv", "w_gate", "w_up")
_ROW = ("wo", "w_down")


def tp_validate(args, degree):
    """The head/FFN divisibility a tp shard needs. Raises ValueError."""
    bad = []
    if args.num_heads % degree:
        bad.append(f"num_heads={args.num_heads}")
    if args.num_kv_heads % degree:
        bad.append(f"num_kv_heads={args.num_kv_heads}")
    if args.intermediate_size % degree:
        bad.append(f"intermediate_size={args.intermediate_size}")
    if bad:
        raise ValueError(
            f"tensor-parallel degree {degree} must divide "
            + ", ".join(bad))


def _leaf_spec(name, leaf, axis):
    """Spec for one stacked [L, ...] layer leaf (or a QuantizedWeight of
    one)."""
    if name in _COL:
        if isinstance(leaf, QuantizedWeight):
            return QuantizedWeight(P(None, None, axis), P(None, axis))
        return P(None, None, axis)
    if name in _ROW:
        if isinstance(leaf, QuantizedWeight):
            # scale is per-OUT-channel; the out dim of a row-parallel
            # shard is unsplit
            return QuantizedWeight(P(None, axis, None), P())
        return P(None, axis, None)
    return QuantizedWeight(P(), P()) if isinstance(leaf, QuantizedWeight) \
        else P()


def llama_tp_specs(params, axis="mp"):
    """PartitionSpec pytree matching a Llama functional param tree (float
    or `quantize_params` int8) for tensor-parallel serving on `axis`."""
    out = {}
    for k, v in params.items():
        if k == "layers":
            out[k] = {n: _leaf_spec(n, leaf, axis) for n, leaf in v.items()}
        elif isinstance(v, QuantizedWeight):
            out[k] = QuantizedWeight(P(), P())
        else:
            out[k] = P()
    return out


def pool_spec(axis="mp"):
    """The paged KV pool `[L, num_pages, nkv, page_size, hd]` shards on
    nkv; stripe caches `[L, S, nkv, max_len, hd]` happen to shard on the
    same axis index."""
    return P(None, None, axis)


def shard_params(params, mesh, axis="mp"):
    """Eagerly place a param tree on `mesh` under the tp specs (the
    sharded arrays are then passed straight into the shard_map'd step
    programs — no resharding on the hot path)."""
    specs = llama_tp_specs(params, axis)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)
