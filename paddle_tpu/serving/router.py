"""SLO-aware multi-model router: one front door over many engines.

The engines below this layer serve ONE model each and treat every
request alike. Production traffic is neither: a chat turn (a human
watching tokens appear) and an overnight batch summarization job hit the
same pool, many models share it, and "fair" FIFO is exactly wrong — the
batch job should soak up idle capacity and GET OUT OF THE WAY the moment
an interactive request needs a slot. This module adds that layer:

  - MULTI-MODEL: a `Router` fronts named backends — llama on a
    `PagedEngine`, GPT-2 on the new `GptEngine` (the stripe scheduler
    re-pointed at `_gpt_forward_cached`, per-row learned positions
    instead of RoPE), and BERT on `BertBackend`, a NON-AUTOREGRESSIVE
    model class: no KV cache, no decode loop — pending embedding
    requests batch into one padded forward per step.
  - SLO CLASSES: every request carries `slo="interactive"|"batch"`.
    The router holds its own per-class queues and feeds an engine's
    admission queue interactive-first; arrival order only breaks ties
    within a class.
  - PREEMPTION: when an interactive request is blocked (no slot / no
    pages) and a batch-class request holds a slot, the router calls the
    paged engine's `preempt()` — the victim's state is just its block
    table + page ids (refcounts still held, so the allocator can
    neither reuse nor evict them) and is `resume()`d once no
    interactive work is waiting, continuing BIT-IDENTICALLY to an
    uninterrupted run. Preempted requests outrank new batch admissions
    (no starvation-by-churn); interactive traffic can starve batch by
    design — that is what the class means.
  - PER-TENANT / PER-MODEL TELEMETRY: labeled series on the router's
    own `MetricsRegistry` — `router_requests` / `router_completed` /
    `router_tokens{model, tenant, slo}` counters, `router_ttft_s` and
    `router_tokens_per_s` histograms per model — exported through the
    same `--telemetry-out` artifact as every other subsystem.

The router is a host-side policy layer: it owns no device programs and
never reaches into a traced step — everything it does is queue surgery
between `step()` calls, so engine-level parity guarantees (greedy
token-for-token, seeded sampling) pass through untouched.
"""

from __future__ import annotations

import functools
import itertools
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import generation as gen
from paddle_tpu.serving.engine import Engine, Request
from paddle_tpu.serving.metrics import Metrics
from paddle_tpu.serving.sampler import pick as _pick
from paddle_tpu.serving.scheduler import bucket_for

__all__ = ["SLO_CLASSES", "GptEngine", "EmbeddingRequest", "BertBackend",
           "Router"]

SLO_CLASSES = ("interactive", "batch")


# -- GPT on the stripe scheduler --------------------------------------------
def _gpt_prefill_traced(params, ids, true_len, ck, cv, slot, temp, top_p,
                        top_k, seeds, *, args, metrics, sample=False):
    # runs once per COMPILE (trace time), not per call
    metrics.inc("prefill_compiles")
    L = ck.shape[0]
    sck = jnp.zeros((L, 1) + ck.shape[2:], ck.dtype)
    scv = jnp.zeros_like(sck)
    logits, sck, scv = gen._gpt_forward_cached(
        params, ids, sck, scv, 0, args, last_idx=true_len - 1)
    first = _pick(logits, sample, temp, top_p, top_k, seeds, true_len)[0]
    ck = jax.lax.dynamic_update_slice_in_dim(ck, sck, slot, axis=1)
    cv = jax.lax.dynamic_update_slice_in_dim(cv, scv, slot, axis=1)
    return ck, cv, first


def _gpt_decode_traced(params, tokens, ck, cv, pos, temp, top_p, top_k,
                       seeds, *, args, metrics, sample=False):
    metrics.inc("decode_compiles")
    logits, ck, cv = gen._gpt_forward_cached(
        params, tokens[:, None], ck, cv, pos, args)
    return ck, cv, _pick(logits, sample, temp, top_p, top_k, seeds, pos + 1)


class GptEngine(Engine):
    """The continuous-batching stripe scheduler serving GPT-2: same
    queue / slot table / retire-admit loop, with the two device programs
    swapped for `_gpt_forward_cached` (learned positions bound `max_len`
    by the position table; per-row decode positions ride the vmapped
    cache write the llama path uses). `params`/`args` come from
    `generation.gpt_params_from_layer` / `GPTGenArgs`."""

    def _setup_device_state(self):
        args = self.args
        if self.max_len > args.max_position_embeddings:
            raise ValueError(
                f"max_len={self.max_len} exceeds the learned position "
                f"table ({args.max_position_embeddings})")
        hd = args.hidden_size // args.num_heads
        self._ck = jnp.zeros((args.num_layers, self.max_slots,
                              args.num_heads, self.max_len, hd),
                             self.params["word_emb"].dtype)
        self._cv = jnp.zeros_like(self._ck)
        donate = self._donate_enabled()
        self._prefill = jax.jit(
            functools.partial(_gpt_prefill_traced, args=args,
                              metrics=self.metrics),
            donate_argnums=(3, 4) if donate else (),
            static_argnames=("sample",))
        self._decode = jax.jit(
            functools.partial(_gpt_decode_traced, args=args,
                              metrics=self.metrics),
            donate_argnums=(2, 3) if donate else (),
            static_argnames=("sample",))

    def _prefill_device(self, req, slot, n):
        bucket = bucket_for(n, self.min_bucket, self.max_len)
        padded = np.full((1, bucket), self.pad_id, np.int32)
        padded[0, :n] = req.prompt_ids
        with self.metrics.timer("prefill_s"):
            self._ck, self._cv, first = self._prefill(
                self.params, jnp.asarray(padded), jnp.int32(n),
                self._ck, self._cv, jnp.int32(slot),
                jnp.float32(req.temperature), jnp.float32(req.top_p),
                jnp.int32(req.top_k), jnp.asarray([req.seed], jnp.int32),
                sample=req.temperature > 0)
            first = int(first)
        return bucket, first

    def _decode_device(self, active):
        with self.metrics.timer("decode_step_s"):
            self._ck, self._cv, nxt = self._decode(
                self.params, jnp.asarray(self._last_tok), self._ck,
                self._cv, jnp.asarray(self._npos), *self._sampling_args(),
                sample=self._sampling_active())
        return np.asarray(nxt)


# -- BERT as a non-autoregressive model class -------------------------------
_embed_ids = itertools.count()


class EmbeddingRequest:
    """A non-autoregressive request: one forward, result on `.embedding`
    (the pooled [CLS] vector). Mirrors `Request`'s bookkeeping surface
    (submit/finish times, ttft) so the router meters both kinds alike."""

    def __init__(self, prompt_ids, request_id=None):
        self.prompt_ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if self.prompt_ids.size == 0:
            raise ValueError("empty prompt")
        self.request_id = (next(_embed_ids) if request_id is None
                           else request_id)
        self.max_new_tokens = 0
        self.token_ids = []
        self.embedding = None
        self.finished = False
        self.finish_reason = None
        self.submit_time = None
        self.first_token_time = None
        self.finish_time = None
        self.ttft_s = None


class BertBackend:
    """Serves a BERT encoder (`models/bert.bert_tiny()` or any
    `BertModel`-shaped layer) as embeddings: each `step()` takes up to
    `max_batch` pending requests, right-pads them to one length with a
    0/1 attention mask, and runs ONE eager forward. No KV state, so
    there is nothing to preempt — SLO ordering is feed order."""

    def __init__(self, model, *, max_batch=8, metrics=None):
        self.model = getattr(model, "bert", model)
        if hasattr(self.model, "eval"):
            self.model.eval()
        self.max_batch = int(max_batch)
        self.metrics = metrics if metrics is not None else Metrics()
        self.queue = deque()
        self.step_count = 0

    def submit(self, req):
        if not isinstance(req, EmbeddingRequest):
            req = EmbeddingRequest(req)
        req.submit_time = time.perf_counter()
        self.queue.append(req)
        self.metrics.inc("requests_submitted")
        return req

    @property
    def busy(self):
        return bool(self.queue)

    def step(self):
        self.step_count += 1
        if not self.queue:
            return {"type": "idle"}
        import paddle_tpu as paddle

        k = min(self.max_batch, len(self.queue))
        batch = [self.queue.popleft() for _ in range(k)]
        s = max(int(r.prompt_ids.size) for r in batch)
        ids = np.zeros((k, s), np.int64)
        mask = np.zeros((k, s), np.int64)
        for i, r in enumerate(batch):
            ids[i, :r.prompt_ids.size] = r.prompt_ids
            mask[i, :r.prompt_ids.size] = 1
        with self.metrics.timer("embed_step_s"):
            _, pooled = self.model(paddle.to_tensor(ids),
                                   attention_mask=paddle.to_tensor(mask))
            pooled = np.asarray(pooled.numpy())
        now = time.perf_counter()
        for i, r in enumerate(batch):
            r.embedding = pooled[i]
            r.finished = True
            r.finish_reason = "embedding"
            r.first_token_time = now
            r.finish_time = now
            r.ttft_s = now - r.submit_time
            self.metrics.observe("ttft_s", r.ttft_s)
        self.metrics.inc("requests_finished", k)
        self.metrics.inc("embeds")
        self.metrics.observe("embed_batch_size", k)
        return {"type": "embed", "count": k}

    def run_until_idle(self):
        while self.busy:
            self.step()


# -- the router --------------------------------------------------------------
class Router:
    """Front door over named backends (`Engine`/`PagedEngine`/`GptEngine`
    instances or `BertBackend`s). See the module docstring for policy;
    mechanically, each `step()` per backend does:

      feed      an interactive request whenever the engine's admission
                queue is empty; else resume a preempted batch request if
                nothing interactive waits and capacity allows; else feed
                a batch request (never while preempted work waits);
      preempt   if the blocked queue head is (or is behind) interactive
                work, no admission is possible, and a batch-class slot
                is decoding on a preemption-capable engine;
      step      the backend's own scheduler once.

    Completions are harvested after every sweep into labeled counters
    and histograms on `self.metrics.registry`.
    """

    def __init__(self, backends, *, metrics=None):
        if not backends:
            raise ValueError("router needs at least one backend")
        self.backends = dict(backends)
        self.metrics = metrics if metrics is not None else Metrics()
        self._waiting = {m: {slo: deque() for slo in SLO_CLASSES}
                         for m in self.backends}
        self._preempted = {m: deque() for m in self.backends}
        self._meta = {}        # id(req) -> (model, tenant, slo)
        self._inflight = []
        self.step_count = 0

    # -- admission -----------------------------------------------------------
    def submit(self, model, prompt_ids, *, tenant="default",
               slo="interactive", max_new_tokens=32, **kw):
        if model not in self.backends:
            raise KeyError(f"unknown model {model!r}; have "
                           f"{sorted(self.backends)}")
        if slo not in SLO_CLASSES:
            raise ValueError(f"slo must be one of {SLO_CLASSES}")
        backend = self.backends[model]
        if isinstance(backend, BertBackend):
            req = EmbeddingRequest(prompt_ids,
                                   request_id=kw.get("request_id"))
        else:
            req = Request(prompt_ids, max_new_tokens, **kw)
        self._meta[id(req)] = (model, tenant, slo)
        self._waiting[model][slo].append(req)
        self._inflight.append(req)
        self.metrics.registry.inc(
            "router_requests",
            labels={"model": model, "tenant": tenant, "slo": slo})
        return req

    def _slo_of(self, req):
        return self._meta.get(id(req), (None, None, "interactive"))[2]

    # -- policy --------------------------------------------------------------
    def _feed(self, model, engine):
        waiting = self._waiting[model]
        if len(engine.queue) > 0:
            return
        if waiting["interactive"]:
            engine.submit(waiting["interactive"].popleft())
            return
        pre = self._preempted[model]
        if pre:
            # preempted batch work outranks NEW batch admissions; while
            # it cannot fit, new batch feeds stay blocked too (they
            # would consume the pages the resume is waiting for)
            if engine.can_resume(pre[0]):
                state = pre.popleft()
                engine.resume(state)
                tenant = self._meta[id(state["req"])][1]
                self.metrics.registry.inc(
                    "router_resumes",
                    labels={"model": model, "tenant": tenant})
            return
        if waiting["batch"]:
            engine.submit(waiting["batch"].popleft())

    def _maybe_preempt(self, model, engine):
        if not hasattr(engine, "preempt"):
            return            # stripe engines checkpoint no KV state
        if not len(engine.queue) or engine._can_prefill():
            return
        head_is_interactive = (
            self._slo_of(engine.queue.peek()) == "interactive"
            or bool(self._waiting[model]["interactive"]))
        if not head_is_interactive:
            return
        streams = getattr(engine, "_chunk_streams", {})
        victims = [s for s in engine.slots.active_slots
                   if self._slo_of(engine.slots.owner(s)) == "batch"
                   and s not in streams]
        if not victims:
            return
        # evict the batch slot with the least decode progress (ties ->
        # highest slot): nothing is lost either way — resume continues
        # bit-identically — but the least-progressed victim frees its
        # reservation refund soonest
        victim = min(victims,
                     key=lambda s: (len(engine.slots.owner(s).token_ids),
                                    -s))
        req = engine.slots.owner(victim)
        state = engine.preempt(victim)
        self._preempted[model].append(state)
        tenant = self._meta[id(req)][1]
        self.metrics.registry.inc(
            "router_preemptions", labels={"model": model, "tenant": tenant})

    # -- the loop ------------------------------------------------------------
    def step(self):
        for model, backend in self.backends.items():
            if isinstance(backend, BertBackend):
                waiting = self._waiting[model]
                for slo in SLO_CLASSES:
                    while waiting[slo]:
                        backend.submit(waiting[slo].popleft())
                backend.step()
                continue
            self._feed(model, backend)
            self._maybe_preempt(model, backend)
            backend.step()
        self.step_count += 1
        self._harvest()
        self._export_depth()

    def _harvest(self):
        reg = self.metrics.registry
        still = []
        for req in self._inflight:
            if not req.finished:
                still.append(req)
                continue
            model, tenant, slo = self._meta.pop(id(req))
            labels = {"model": model, "tenant": tenant, "slo": slo}
            reg.inc("router_completed", labels=labels)
            reg.inc("router_tokens", len(req.token_ids),
                    labels={"model": model, "tenant": tenant})
            if req.ttft_s is not None:
                reg.observe("router_ttft_s", req.ttft_s,
                            labels={"model": model})
            dur = (req.finish_time or 0) - (req.submit_time or 0)
            if req.token_ids and dur > 0:
                reg.observe("router_tokens_per_s",
                            len(req.token_ids) / dur,
                            labels={"model": model})
        self._inflight = still

    def _export_depth(self):
        reg = self.metrics.registry
        for model, waiting in self._waiting.items():
            for slo in SLO_CLASSES:
                reg.set_gauge("router_queue_depth", len(waiting[slo]),
                              labels={"model": model, "slo": slo})
            reg.set_gauge("router_preempted_held",
                          len(self._preempted[model]),
                          labels={"model": model})

    def _backend_busy(self, backend):
        if isinstance(backend, BertBackend):
            return backend.busy
        return bool(len(backend.queue) or backend.slots.active_slots
                    or getattr(backend, "_chunk_streams", None))

    @property
    def busy(self):
        return bool(self._inflight
                    or any(self._backend_busy(b)
                           for b in self.backends.values())
                    or any(self._preempted.values()))

    def run_until_idle(self):
        while self.busy:
            self.step()

    def serve(self, requests):
        """Submit a list of dicts (`model`, `prompt` + Request kwargs +
        optional `tenant`/`slo`), run to completion, return the request
        objects in order."""
        out = [self.submit(r["model"], r["prompt"],
                           tenant=r.get("tenant", "default"),
                           slo=r.get("slo", "interactive"),
                           max_new_tokens=r.get("max_new_tokens", 32),
                           **{k: r[k] for k in ("temperature", "top_p",
                                                "top_k", "seed",
                                                "eos_token_id",
                                                "request_id") if k in r})
               for r in requests]
        self.run_until_idle()
        return out

    def replay(self, trace):
        """Replay an arrival trace: `tools/serving_trace` entries plus
        `model` (+ optional `tenant`/`slo`) keys; arrival steps are
        ROUTER steps. Returns the request objects in trace order."""
        pending = sorted(trace, key=lambda t: t["arrival_step"])
        out = {}
        i = 0
        while i < len(pending) or self.busy:
            while (i < len(pending)
                   and pending[i]["arrival_step"] <= self.step_count):
                t = pending[i]
                kw = {k: t[k] for k in ("temperature", "top_p", "top_k",
                                        "seed", "eos_token_id",
                                        "request_id") if k in t}
                out[id(t)] = self.submit(
                    t["model"], t["prompt"],
                    tenant=t.get("tenant", "default"),
                    slo=t.get("slo", "interactive"),
                    max_new_tokens=t.get("max_new_tokens", 8), **kw)
                i += 1
            self.step()
        return [out[id(t)] for t in trace]
