"""paddle_tpu.serving — continuous-batching LLM serving.

Two engines share one iteration-level scheduler (Orca-style):

  - `Engine` (serving/engine.py): slot-based KV cache — one `max_len`
    stripe per slot. Simple, but HBM caps concurrency at S stripes.
  - `PagedEngine` (serving/paged_engine.py): paged KV cache — a fixed
    page pool + per-slot block tables (`serving/block_manager.py`:
    refcounted pages, copy-on-write, LRU eviction) with HASH-BASED
    PREFIX REUSE: full pages of every prefilled prompt are registered in
    an exact-match hash chain, so a shared system prompt is prefilled
    once and later requests start decoding after a block-table lookup.
    Admission allocates pages on demand (worst case reserved up front),
    so far more concurrent requests fit the same KV HBM.

`serving/scheduler.py` holds the admission queue / length buckets /
slot table / page math; `serving/metrics.py` the counters (queue depth,
TTFT, tokens/sec, occupancy, compile counts, prefix-cache hit rate,
pages in use/free, COW copies) that also back
`inference.Config.enable_profile()`.

    from paddle_tpu.serving import PagedEngine, Request

    eng = PagedEngine(params, args, max_slots=32, max_len=1024,
                      page_size=64, num_pages=256)
    req = eng.submit(Request(prompt_ids, max_new_tokens=64,
                             eos_token_id=2, stream_cb=on_token))
    eng.run_until_idle()          # req.token_ids, req.ttft_s, ...
    print(eng.metrics.summary())

`bench.py --serving` replays deterministic arrival traces
(`tools/serving_trace.py`, incl. shared-prefix traces) and reports
throughput + TTFT vs sequential `generate`, plus a stripe-vs-paged
comparison at equal KV-cache HBM.
"""

from paddle_tpu.serving.block_manager import NULL_PAGE, BlockAllocator
from paddle_tpu.serving.engine import Engine, Request
from paddle_tpu.serving.metrics import Metrics
from paddle_tpu.serving.paged_engine import PagedEngine
from paddle_tpu.serving.scheduler import (AdmissionQueue, SlotTable,
                                          bucket_for, pages_for)

__all__ = ["Engine", "PagedEngine", "Request", "Metrics", "BlockAllocator",
           "NULL_PAGE", "AdmissionQueue", "SlotTable", "bucket_for",
           "pages_for"]
