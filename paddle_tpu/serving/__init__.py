"""paddle_tpu.serving — continuous-batching LLM serving.

`Engine` schedules requests at iteration granularity over a slot-based
KV cache (`serving/engine.py`); `serving/scheduler.py` holds the
admission queue / length buckets / slot table; `serving/metrics.py` the
counters (queue depth, TTFT, tokens/sec, slot occupancy, compile counts)
that also back `inference.Config.enable_profile()`.

    from paddle_tpu.serving import Engine, Request

    eng = Engine(params, args, max_slots=8, max_len=512)
    req = eng.submit(Request(prompt_ids, max_new_tokens=64,
                             eos_token_id=2, stream_cb=on_token))
    eng.run_until_idle()          # req.token_ids, req.ttft_s, ...
    print(eng.metrics.summary())

`bench.py --serving` replays a deterministic Poisson-ish arrival trace
(`tools/serving_trace.py`) and reports throughput + TTFT against
sequential `generate`.
"""

from paddle_tpu.serving.engine import Engine, Request
from paddle_tpu.serving.metrics import Metrics
from paddle_tpu.serving.scheduler import (AdmissionQueue, SlotTable,
                                          bucket_for)

__all__ = ["Engine", "Request", "Metrics", "AdmissionQueue", "SlotTable",
           "bucket_for"]
