"""paddle_tpu.serving — continuous-batching LLM serving.

Two engines share one iteration-level scheduler (Orca-style):

  - `Engine` (serving/engine.py): slot-based KV cache — one `max_len`
    stripe per slot. Simple, but HBM caps concurrency at S stripes.
  - `PagedEngine` (serving/paged_engine.py): paged KV cache — a fixed
    page pool + per-slot block tables (`serving/block_manager.py`:
    refcounted pages, copy-on-write, leaf-LRU eviction) with RADIX-TREE
    PREFIX REUSE: every prefilled prompt is registered in a radix tree
    over token sequences, so a shared system prompt is prefilled once
    and later requests reuse it at TOKEN granularity — a mid-page
    divergence still shares the straddled page via a COW page split
    (`prefix_policy="hash"` keeps the PR-8 exact-match chain as the
    baseline). Admission allocates pages on demand (worst case reserved
    up front), so far more concurrent requests fit the same KV HBM —
    and `kv_dtype="int8"` quantizes the page pool itself (int8 codes +
    per-(page, kv-head) absmax scales, dequantized inside the paged
    kernel) for ~2x the pages again at the same byte budget, with a
    top-1 agreement parity bar vs the model-dtype pool.

The paged engine stacks the three serving-throughput levers (ISSUE 14),
all preserving exact greedy parity with sequential `generate`:

  - TENSOR PARALLELISM: `PagedEngine(mesh=...)` runs every step as a
    shard_map SPMD program over a mesh `mp` axis — Megatron weight
    shards, page pool sharded on nkv, block tables replicated
    (`serving/tp.py` placement);
  - CHUNKED PREFILL: `prefill_chunk=` streams long prompts in
    page-aligned chunks interleaved with decode steps (+ anti-convoy
    short-prompt bypass), keeping TTFT flat under long-prompt bursts;
  - SPECULATIVE DECODING (`serving/spec_decode.py`): `draft_params=`/
    `draft_args=` (see `generation.draft_from_params`) propose
    `spec_tokens` draft tokens in one traced scan and verify the window
    in one batched paged forward — greedy exact-match acceptance, then
    the block table rolls back to the committed watermark (rejected
    window pages return to the pool);
  - per-request sampling (`serving/sampler.py`): `Request(temperature=,
    top_p=, top_k=, seed=)` as traced per-row vectors (greedy rows stay
    bit-exact argmax in mixed batches; seeds make tokens
    batch-independent).

Above the single-engine layer sit two ISSUE-20 subsystems:

  - DISAGGREGATED PREFILL/DECODE (`serving/disagg.py`): `PrefillWorker`
    and `DecodeWorker` are role-restricted `PagedEngine`s — prefill
    never decodes, decode never admits locally. A finished prefill
    becomes a `KVHandoff` (request identity + sampling state + the
    slot's KV page contents, bf16 or int8 `QuantizedKVPage`s verbatim)
    shipped over a transport (`LocalTransport` in-process,
    `StoreTransport` over the TCPStore in the 2-process rig); the
    decode side re-scatters the pages into fresh pool pages and seats
    the request mid-flight — greedy output stays token-for-token equal
    to a monolithic engine, and the steady decode stream keeps its
    per-step rate while the other role absorbs long-prompt bursts.
    `DisaggServer` wires one prefill + one decode worker behind a
    single submit/step surface.
  - SLO-AWARE MULTI-MODEL ROUTER (`serving/router.py`): a `Router`
    fronts named backends — llama (`PagedEngine`), GPT-2 (`GptEngine`,
    the stripe scheduler re-pointed at `_gpt_forward_cached`), BERT
    embeddings (`BertBackend`, batched non-autoregressive forwards) —
    with `slo="interactive"|"batch"` classes, preemption of batch
    slots (block-table checkpoint, bit-identical `resume`), and
    per-model/per-tenant labeled counters on its registry.

`serving/scheduler.py` holds the admission queue / length buckets /
slot table / page math; `serving/metrics.py` the counters (queue depth,
TTFT, tokens/sec, occupancy, compile counts, prefix-cache hit rate,
pages in use/free, COW copies, prefill chunks, draft proposed/accepted,
hand-off counts/bytes/latency, preemptions/resumes) that also back
`inference.Config.enable_profile()`.

    from paddle_tpu.serving import PagedEngine, Request

    eng = PagedEngine(params, args, max_slots=32, max_len=1024,
                      page_size=64, num_pages=256)
    req = eng.submit(Request(prompt_ids, max_new_tokens=64,
                             eos_token_id=2, stream_cb=on_token))
    eng.run_until_idle()          # req.token_ids, req.ttft_s, ...
    print(eng.metrics.summary())

`bench.py --serving` replays deterministic arrival traces
(`tools/serving_trace.py`, incl. shared-prefix and mixed long/short
traces) and reports throughput + TTFT vs sequential `generate`, plus a
stripe-vs-paged comparison at equal KV-cache HBM, a chunked-vs-
monolithic TTFT leg, and a speculative-vs-greedy tokens/sec leg.
"""

from paddle_tpu.serving.block_manager import (NULL_PAGE, BlockAllocator,
                                              PrefixMatch)
from paddle_tpu.serving.disagg import (DecodeWorker, DisaggServer,
                                       KVHandoff, LocalTransport,
                                       PrefillWorker, StoreTransport)
from paddle_tpu.serving.engine import Engine, Request
from paddle_tpu.serving.metrics import Metrics
from paddle_tpu.serving.paged_engine import PagedEngine
from paddle_tpu.serving.router import (BertBackend, EmbeddingRequest,
                                       GptEngine, Router)
from paddle_tpu.serving.sampler import SlotSampler
from paddle_tpu.serving.scheduler import (AdmissionQueue, SlotTable,
                                          bucket_for, pages_for)
from paddle_tpu.serving.spec_decode import SpecDecoder

__all__ = ["Engine", "PagedEngine", "Request", "Metrics", "BlockAllocator",
           "PrefixMatch", "NULL_PAGE", "AdmissionQueue", "SlotTable",
           "SlotSampler", "SpecDecoder", "bucket_for", "pages_for",
           "PrefillWorker", "DecodeWorker", "DisaggServer", "KVHandoff",
           "LocalTransport", "StoreTransport", "Router", "GptEngine",
           "BertBackend", "EmbeddingRequest"]
