"""Per-request sampler shared by the serving engines.

One next-token selection waist for every traced engine step (stripe
prefill/decode, paged prefill/decode — `pick`), plus the host-side
per-slot sampling state both engines carry (`SlotSampler`). The math
itself lives in `models/generation._sample` (temperature, nucleus
top-p, top-k, gumbel-max per-row draws) so the OFFLINE
`generate(temperature=, top_p=, top_k=, seeds=)` path and the serving
engines share one implementation; keys come from
`generation._row_keys` — the one (seed, position) derivation, so a
request's randomness is a pure function of its own seed and the
position being sampled, never of its batch-mates.

Greedy is the default and stays the fast path: `pick(sample=False)`
compiles to a bare argmax (no sampling ops in the program), and inside
a mixed batch greedy rows (temperature 0) remain bit-exact argmax.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import generation as gen

__all__ = ["pick", "SlotSampler"]


def pick(logits, sample, temp, top_p, top_k, seeds, pos):
    """Next-token selection shared by every traced engine step: exact
    argmax for the greedy program (sample=False — the default, whose
    program contains no sampling ops at all), the per-row `_sample`
    machinery otherwise. Keys come from `generation._row_keys` — the ONE
    (seed, position) derivation `generate(seeds=...)` also uses."""
    if not sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return gen._sample(logits, True, temp, top_p, None, top_k,
                       row_keys=gen._row_keys(seeds, pos))


class SlotSampler:
    """Host-side per-slot sampling parameters (greedy defaults; loaded
    at admission, cleared at retire). The arrays feed the traced step
    programs as per-row operands, so changing a request's sampling
    settings never recompiles."""

    def __init__(self, max_slots):
        self.max_slots = int(max_slots)
        self._temp = np.zeros(self.max_slots, np.float32)
        self._top_p = np.ones(self.max_slots, np.float32)
        self._top_k = np.zeros(self.max_slots, np.int32)
        self._seed = np.zeros(self.max_slots, np.int32)

    def admit(self, slot, req):
        self._temp[slot] = req.temperature
        self._top_p[slot] = req.top_p
        self._top_k[slot] = req.top_k
        self._seed[slot] = np.int32(req.seed)

    def clear(self, slot):
        self._temp[slot] = 0.0
        self._top_p[slot] = 1.0
        self._top_k[slot] = 0
        self._seed[slot] = 0

    def reset(self):
        for slot in range(self.max_slots):
            self.clear(slot)

    def any_sampling(self, slots):
        """True when any of `slots` samples — selects the step-program
        variant (greedy-only traffic never compiles the sampling ops)."""
        return any(self._temp[s] > 0 for s in slots)

    def device_args(self):
        """The per-row operands the traced `pick` consumes."""
        return (jnp.asarray(self._temp), jnp.asarray(self._top_p),
                jnp.asarray(self._top_k), jnp.asarray(self._seed))
