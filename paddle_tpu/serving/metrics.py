"""Serving metrics: counters / gauges / observations for the engine and
the inference Predictor.

The reference ships a GPU-serving metrics layer in PaddleNLP's serving
stack (queue depth, first-token latency, QPS); here one small dependency-
free registry backs three consumers:

  - `serving.Engine` — queue depth, slot occupancy, per-step tokens/sec,
    time-to-first-token, and COMPILE COUNTS (incremented at trace time:
    the jitted step bodies bump a counter as a Python side effect, which
    runs exactly once per XLA compilation — a cached call never re-enters
    the traced Python, so the counter is precisely "programs built");
  - `inference.Config.enable_profile()` — Predictor.run wall time + call
    counts, retrievable via `Predictor.summary()`;
  - `bench.py --serving` — the throughput/TTFT artifact.

Nothing here runs inside traced code except the trace-time counter bumps;
no wall-clock reads ever enter a jitted program.
"""

from __future__ import annotations

import contextlib
import time

__all__ = ["Metrics"]


class Metrics:
    """Counters (monotonic), gauges (last value + max), observations
    (count/sum/min/max streaming summaries)."""

    def __init__(self):
        self._counters = {}
        self._gauges = {}
        self._obs = {}

    # -- counters -----------------------------------------------------------
    def inc(self, name, value=1):
        self._counters[name] = self._counters.get(name, 0) + value

    def counter(self, name):
        return self._counters.get(name, 0)

    # -- gauges -------------------------------------------------------------
    def set_gauge(self, name, value):
        g = self._gauges.setdefault(name, {"value": 0, "max": value})
        g["value"] = value
        g["max"] = max(g["max"], value)

    def gauge(self, name):
        g = self._gauges.get(name)
        return g["value"] if g else 0

    # -- observations -------------------------------------------------------
    def observe(self, name, value):
        value = float(value)
        o = self._obs.get(name)
        if o is None:
            self._obs[name] = {"count": 1, "sum": value, "min": value,
                               "max": value}
        else:
            o["count"] += 1
            o["sum"] += value
            o["min"] = min(o["min"], value)
            o["max"] = max(o["max"], value)

    def observation(self, name):
        o = self._obs.get(name)
        if not o:
            return None
        return dict(o, mean=o["sum"] / o["count"])

    @contextlib.contextmanager
    def timer(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    # -- reporting ----------------------------------------------------------
    def summary(self):
        return {
            "counters": dict(self._counters),
            "gauges": {k: dict(v) for k, v in self._gauges.items()},
            "observations": {k: self.observation(k) for k in self._obs},
        }

    def reset(self, keep_counters=()):
        """Clear everything except the named counters — the engine's
        compile counters survive a reset so warmup + timed benchmark runs
        on one engine still report honest compile totals."""
        kept = {k: v for k, v in self._counters.items() if k in keep_counters}
        self._counters = kept
        self._gauges = {}
        self._obs = {}
