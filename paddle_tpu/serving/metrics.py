"""Serving metrics: back-compat facade over the framework-wide registry.

Historically this module owned a small dict-based registry for the serving
engine; PR 6 promoted it to `paddle_tpu.observability.MetricsRegistry`
(thread-safe, labeled series, fixed-bucket histograms with p50/p95/p99,
JSON + Prometheus exporters) and this `Metrics` class became a thin shim
keeping the original call surface:

  - `serving.Engine` — queue depth, slot occupancy, per-step tokens/sec,
    time-to-first-token (wall seconds AND engine steps), and COMPILE
    COUNTS (incremented at trace time: the jitted step bodies bump a
    counter as a Python side effect, which runs exactly once per XLA
    compilation — a cached call never re-enters the traced Python, so the
    counter is precisely "programs built");
  - the chunked-prefill / speculative-decoding series (ROADMAP 1's
    acceptance metrics): `prefill_stall_steps` gauge (scheduler steps a
    prefill took while decodable slots waited — the stall chunking
    flattens), `prefill_chunks`/`chunked_prefills` counters +
    `chunks_per_prompt` histogram, `spec_acceptance_rate` histogram and
    `draft_tokens_proposed`/`draft_tokens_accepted` counters (+
    `spec_commit_len`, `spec_rounds`, `spec_pages_rewound` for the
    roll-back path);
  - `inference.Config.enable_profile()` — Predictor.run wall time + call
    counts, retrievable via `Predictor.summary()`;
  - `bench.py --serving` — the throughput/TTFT artifact, now with TTFT
    p50/p95/p99 (ROADMAP 2's acceptance metric).

Mutators are thread-safe: streaming callbacks and the comm-monitor
heartbeat thread can race `inc`/`observe` against the scheduler loop.
Nothing here runs inside traced code except the trace-time counter bumps;
no wall-clock reads ever enter a jitted program.
"""

from __future__ import annotations

from paddle_tpu.observability.registry import MetricsRegistry

__all__ = ["Metrics"]


class Metrics:
    """Counters (monotonic), gauges (last value + max), observations
    (count/sum/min/max/mean + p50/p95/p99 quantile summaries)."""

    def __init__(self, registry=None):
        # each Metrics() gets its OWN registry: reset() clears the registry
        # wholesale and summary() reads unlabeled series, so this registry
        # must stay engine-private. Do NOT pass the process-global registry
        # here — Engine.reset() would wipe every other subsystem's
        # telemetry; publish serving numbers via the bench record /
        # telemetry artifacts instead.
        self.registry = registry if registry is not None else MetricsRegistry()

    # -- counters -----------------------------------------------------------
    def inc(self, name, value=1):
        self.registry.inc(name, value)

    def counter(self, name):
        return self.registry.counter(name)

    # -- gauges -------------------------------------------------------------
    def set_gauge(self, name, value):
        self.registry.set_gauge(name, value)

    def gauge(self, name):
        return self.registry.gauge(name)

    # -- observations -------------------------------------------------------
    def observe(self, name, value):
        self.registry.observe(name, float(value))

    def observation(self, name):
        return self.registry.observation(name)

    def timer(self, name):
        return self.registry.timer(name)

    # -- reporting ----------------------------------------------------------
    def summary(self):
        snap = self.registry.snapshot()  # one atomic read
        return {
            "counters": {k: v.get("", 0)
                         for k, v in snap["counters"].items()},
            "gauges": {k: dict(v.get("", {"value": 0, "max": 0}))
                       for k, v in snap["gauges"].items()},
            "observations": {k: v.get("")
                             for k, v in snap["histograms"].items()},
        }

    def to_prometheus(self):
        return self.registry.to_prometheus()

    def reset(self, keep_counters=()):
        """Clear everything except the named counters — the engine's
        compile counters survive a reset so warmup + timed benchmark runs
        on one engine still report honest compile totals."""
        self.registry.reset(keep_counters=keep_counters)
