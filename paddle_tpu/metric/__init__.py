"""Metrics (reference: `python/paddle/metric/metrics.py`)."""

import numpy as np

from paddle_tpu.core.tensor import Tensor


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        p = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        l = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        order = np.argsort(-p, axis=-1)[..., : self.maxk]
        correct = order == l[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = correct.numpy() if isinstance(correct, Tensor) else np.asarray(correct)
        num = c.shape[0] if c.ndim > 0 else 1
        res = []
        for i, k in enumerate(self.topk):
            acc_k = c[..., :k].sum() / num
            self.total[i] += c[..., :k].sum()
            self.count[i] += num
            res.append(acc_k)
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.rint(np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)).astype(int).reshape(-1)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.rint(np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)).astype(int).reshape(-1)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__()
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels).reshape(-1)
        if p.ndim == 2:
            p = p[:, -1]
        idx = np.minimum((p * self.num_thresholds).astype(int), self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp

    p = input._data
    l = label._data
    if l.ndim == p.ndim and l.shape[-1] == 1:
        l = l[..., 0]
    topk_idx = jnp.argsort(-p, axis=-1)[..., :k]
    correct_mask = jnp.any(topk_idx == l[..., None], axis=-1)
    return Tensor(jnp.mean(correct_mask.astype(jnp.float32)))
