"""paddle.geometric (reference: `python/paddle/geometric/`, ~1.7K LoC;
kernels `paddle/phi/kernels/*/segment_pool_kernel.*`,
`graph_send_recv_kernel.*`, `graph_send_ue_recv_kernel.*`).

TPU-native design: every message-passing primitive is a segment reduction,
which XLA lowers to sorted scatter-adds — `jax.ops.segment_*` on static
shapes. Graph *sampling* ops (khop/neighbors) are host-side,
dynamic-shape operations and stay out of the compiled path (see
OP_COVERAGE.md skips).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, apply

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
]


def _num_segments(ids, n):
    if n is not None:
        return int(n)
    return int(jax.device_get(ids._data.max())) + 1 if ids.shape[0] else 0


def segment_sum(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    return apply(lambda d, i: jax.ops.segment_sum(d, i, num_segments=n),
                 data, segment_ids, _name="segment_sum")


def segment_mean(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)

    def fn(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones_like(i, d.dtype), i, num_segments=n)
        c = c.reshape((-1,) + (1,) * (d.ndim - 1))
        return s / jnp.maximum(c, 1)

    return apply(fn, data, segment_ids, _name="segment_mean")


def segment_max(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)

    def fn(d, i):
        out = jax.ops.segment_max(d, i, num_segments=n)
        # empty segments: reference returns 0, jax returns -inf
        return jnp.where(jnp.isfinite(out), out, 0)

    return apply(fn, data, segment_ids, _name="segment_max")


def segment_min(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)

    def fn(d, i):
        out = jax.ops.segment_min(d, i, num_segments=n)
        return jnp.where(jnp.isfinite(out), out, 0)

    return apply(fn, data, segment_ids, _name="segment_min")


_REDUCERS = {"sum": jax.ops.segment_sum, "add": jax.ops.segment_sum,
             "max": jax.ops.segment_max, "min": jax.ops.segment_min}


def _reduce(msg, dst, n, pool):
    if pool in ("sum", "add"):
        return jax.ops.segment_sum(msg, dst, num_segments=n)
    if pool == "mean":
        s = jax.ops.segment_sum(msg, dst, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones_like(dst, msg.dtype), dst,
                                num_segments=n)
        return s / jnp.maximum(c.reshape((-1,) + (1,) * (msg.ndim - 1)), 1)
    out = _REDUCERS[pool](msg, dst, num_segments=n)
    return jnp.where(jnp.isfinite(out), out, 0)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather src features, reduce at dst (reference
    `geometric/message_passing/send_recv.py` send_u_recv)."""
    n = out_size or x.shape[0]
    return apply(lambda a, s, d: _reduce(a[s], d, int(n), reduce_op),
                 x, src_index, dst_index, _name="send_u_recv")


_MSG_OPS = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
            "div": jnp.divide}


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine src node features with edge features, reduce at dst."""
    n = out_size or x.shape[0]
    mop = _MSG_OPS[message_op]
    return apply(lambda a, e, s, d: _reduce(mop(a[s], e), d, int(n), reduce_op),
                 x, y, src_index, dst_index, _name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from src (x) and dst (y) node features."""
    mop = _MSG_OPS[message_op]
    return apply(lambda a, b, s, d: mop(a[s], b[d]),
                 x, y, src_index, dst_index, _name="send_uv")


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (host-side; reference
    `geometric/reindex.py`). Runs on host — dynamic output shapes."""
    import numpy as np

    xs = np.asarray(jax.device_get(x._data))
    nb = np.asarray(jax.device_get(neighbors._data))
    # reference semantics: x nodes keep their order first, then new ones
    order = {v: i for i, v in enumerate(xs)}
    nxt = len(xs)
    mapping = {}
    for v in np.concatenate([xs, nb]):
        if v not in mapping:
            if v in order:
                mapping[v] = order[v]
            else:
                mapping[v] = nxt
                nxt += 1
    reindex_src = np.asarray([mapping[v] for v in nb], np.int64)
    reindex_dst = np.repeat(np.arange(len(xs), dtype=np.int64),
                            np.asarray(jax.device_get(count._data)))
    out_nodes = np.asarray(sorted(mapping, key=mapping.get), np.int64)
    return (Tensor(jnp.asarray(reindex_src)), Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(out_nodes)))
