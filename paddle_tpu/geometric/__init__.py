"""paddle.geometric (reference: `python/paddle/geometric/`, ~1.7K LoC;
kernels `paddle/phi/kernels/*/segment_pool_kernel.*`,
`graph_send_recv_kernel.*`, `graph_send_ue_recv_kernel.*`).

TPU-native design: every message-passing primitive is a segment reduction,
which XLA lowers to sorted scatter-adds — `jax.ops.segment_*` on static
shapes. Graph *sampling* ops (khop/neighbors) are host-side,
dynamic-shape operations and stay out of the compiled path (see
OP_COVERAGE.md skips).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, apply

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
]


def _num_segments(ids, n):
    if n is not None:
        return int(n)
    return int(jax.device_get(ids._data.max())) + 1 if ids.shape[0] else 0


def segment_sum(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)
    return apply(lambda d, i: jax.ops.segment_sum(d, i, num_segments=n),
                 data, segment_ids, _name="segment_sum")


def segment_mean(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)

    def fn(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones_like(i, d.dtype), i, num_segments=n)
        c = c.reshape((-1,) + (1,) * (d.ndim - 1))
        return s / jnp.maximum(c, 1)

    return apply(fn, data, segment_ids, _name="segment_mean")


def segment_max(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)

    def fn(d, i):
        out = jax.ops.segment_max(d, i, num_segments=n)
        # empty segments: reference returns 0, jax returns -inf
        return jnp.where(jnp.isfinite(out), out, 0)

    return apply(fn, data, segment_ids, _name="segment_max")


def segment_min(data, segment_ids, name=None):
    n = _num_segments(segment_ids, None)

    def fn(d, i):
        out = jax.ops.segment_min(d, i, num_segments=n)
        return jnp.where(jnp.isfinite(out), out, 0)

    return apply(fn, data, segment_ids, _name="segment_min")


_REDUCERS = {"sum": jax.ops.segment_sum, "add": jax.ops.segment_sum,
             "max": jax.ops.segment_max, "min": jax.ops.segment_min}


def _reduce(msg, dst, n, pool):
    if pool in ("sum", "add"):
        return jax.ops.segment_sum(msg, dst, num_segments=n)
    if pool == "mean":
        s = jax.ops.segment_sum(msg, dst, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones_like(dst, msg.dtype), dst,
                                num_segments=n)
        return s / jnp.maximum(c.reshape((-1,) + (1,) * (msg.ndim - 1)), 1)
    out = _REDUCERS[pool](msg, dst, num_segments=n)
    return jnp.where(jnp.isfinite(out), out, 0)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather src features, reduce at dst (reference
    `geometric/message_passing/send_recv.py` send_u_recv)."""
    n = out_size or x.shape[0]
    return apply(lambda a, s, d: _reduce(a[s], d, int(n), reduce_op),
                 x, src_index, dst_index, _name="send_u_recv")


_MSG_OPS = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
            "div": jnp.divide}


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine src node features with edge features, reduce at dst."""
    n = out_size or x.shape[0]
    mop = _MSG_OPS[message_op]
    return apply(lambda a, e, s, d: _reduce(mop(a[s], e), d, int(n), reduce_op),
                 x, y, src_index, dst_index, _name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from src (x) and dst (y) node features."""
    mop = _MSG_OPS[message_op]
    return apply(lambda a, b, s, d: mop(a[s], b[d]),
                 x, y, src_index, dst_index, _name="send_uv")


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (host-side; reference
    `geometric/reindex.py`). Runs on host — dynamic output shapes."""
    import numpy as np

    xs = np.asarray(jax.device_get(x._data))
    nb = np.asarray(jax.device_get(neighbors._data))
    # reference semantics: x nodes keep their order first, then new ones
    order = {v: i for i, v in enumerate(xs)}
    nxt = len(xs)
    mapping = {}
    for v in np.concatenate([xs, nb]):
        if v not in mapping:
            if v in order:
                mapping[v] = order[v]
            else:
                mapping[v] = nxt
                nxt += 1
    reindex_src = np.asarray([mapping[v] for v in nb], np.int64)
    reindex_dst = np.repeat(np.arange(len(xs), dtype=np.int64),
                            np.asarray(jax.device_get(count._data)))
    out_nodes = np.asarray(sorted(mapping, key=mapping.get), np.int64)
    return (Tensor(jnp.asarray(reindex_src)), Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(out_nodes)))


# -- graph neighbourhood sampling (r5 op tail; reference
# -- `phi/kernels/cpu/graph_sample_neighbors_kernel.cc` etc.) ---------------


def _np1d(t, dtype):
    """Any tensor-like -> flat numpy array (one unwrap idiom for all
    three samplers)."""
    import numpy as np

    return np.asarray(getattr(t, "_data", t), dtype).reshape(-1)


def _csc(row, colptr):
    import numpy as np

    return _np1d(row, np.int64), _np1d(colptr, np.int64)


def _check_eids(eids, return_eids):
    if return_eids and eids is None:
        raise ValueError("return_eids=True requires eids (the edge-id "
                         "tensor aligned with `row`)")


def graph_sample_neighbors(row, colptr, x, eids=None, perm_buffer=None,
                           sample_size=-1, return_eids=False,
                           flag_perm_buffer=False, name=None):
    """Uniform neighbour sampling on a CSC graph (reference
    graph_sample_neighbors / python `geometric.sample_neighbors`): for
    each node in x, draw up to sample_size neighbours from
    row[colptr[n]:colptr[n+1]]. Host-side (dynamic output), like the
    reference CPU kernel. Returns (out, out_count[, out_eids])."""
    import numpy as np

    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor

    r, c = _csc(row, colptr)
    _check_eids(eids, return_eids)
    xs = _np1d(x, np.int64)
    ev = _np1d(eids, np.int64) if eids is not None else None
    rng = np.random.default_rng()
    outs, counts, oeids = [], [], []
    for n in xs:
        lo, hi = int(c[n]), int(c[n + 1])
        deg = hi - lo
        if sample_size in (-1, None) or deg <= sample_size:
            sel = np.arange(lo, hi)
        else:
            sel = lo + rng.choice(deg, size=sample_size, replace=False)
        outs.append(r[sel])
        counts.append(len(sel))
        if return_eids and ev is not None:
            oeids.append(ev[sel])
    out = (np.concatenate(outs) if outs else np.zeros(0, np.int64))
    res = (Tensor(jnp.asarray(out)),
           Tensor(jnp.asarray(np.asarray(counts, np.int32))))
    if return_eids and ev is not None:
        res = res + (Tensor(jnp.asarray(
            np.concatenate(oeids) if oeids else np.zeros(0, np.int64))),)
    return res


sample_neighbors = graph_sample_neighbors  # python-api name


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              eids=None, sample_size=-1, return_eids=False,
                              name=None):
    """Weighted neighbour sampling (reference weighted_sample_neighbors):
    neighbours drawn without replacement with probability proportional to
    edge_weight (A-Res weighted reservoir, like the reference kernel)."""
    import numpy as np

    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor

    r, c = _csc(row, colptr)
    _check_eids(eids, return_eids)
    w = _np1d(edge_weight, np.float64)
    xs = _np1d(input_nodes, np.int64)
    ev = _np1d(eids, np.int64) if eids is not None else None
    rng = np.random.default_rng()
    outs, counts, oeids = [], [], []
    for n in xs:
        lo, hi = int(c[n]), int(c[n + 1])
        deg = hi - lo
        idx = np.arange(lo, hi)
        if not (sample_size in (-1, None) or deg <= sample_size):
            # A-Res: keys u^(1/w), take top sample_size
            keys = rng.random(deg) ** (1.0 / np.maximum(w[idx], 1e-12))
            idx = idx[np.argsort(-keys)[:sample_size]]
        outs.append(r[idx])
        counts.append(len(idx))
        if return_eids and ev is not None:
            oeids.append(ev[idx])
    out = (np.concatenate(outs) if outs else np.zeros(0, np.int64))
    res = (Tensor(jnp.asarray(out)),
           Tensor(jnp.asarray(np.asarray(counts, np.int32))))
    if return_eids and ev is not None:
        res = res + (Tensor(jnp.asarray(
            np.concatenate(oeids) if oeids else np.zeros(0, np.int64))),)
    return res


def graph_khop_sampler(row, colptr, x, eids=None, sample_sizes=(),
                       return_eids=False, name=None):
    """Multi-hop sampling + subgraph reindexing (reference
    graph_khop_sampler / python `geometric.khop_sampler`): hop h samples
    sample_sizes[h] neighbours of the frontier; the union of visited
    nodes is renumbered [x first, then new nodes in discovery order].
    Returns (out_src, out_dst, sample_index, reindex_x[, out_eids]) —
    edges in LOCAL ids, the local->global map, and x's local ids."""
    import numpy as np

    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor

    r, c = _csc(row, colptr)
    _check_eids(eids, return_eids)
    xs = _np1d(x, np.int64)
    ev = _np1d(eids, np.int64) if eids is not None else None
    rng = np.random.default_rng()
    local = {int(n): i for i, n in enumerate(xs)}
    order = list(xs)
    src, dst, es = [], [], []
    frontier = list(xs)
    for size in sample_sizes:
        nxt = []
        for n in frontier:
            lo, hi = int(c[n]), int(c[n + 1])
            deg = hi - lo
            if size in (-1, None) or deg <= size:
                sel = np.arange(lo, hi)
            else:
                sel = lo + rng.choice(deg, size=size, replace=False)
            for j in sel:
                nb = int(r[j])
                if nb not in local:
                    local[nb] = len(order)
                    order.append(nb)
                    nxt.append(nb)
                src.append(local[nb])
                dst.append(local[int(n)])
                if ev is not None:
                    es.append(ev[j])
        frontier = nxt
    res = (Tensor(jnp.asarray(np.asarray(src, np.int64))),
           Tensor(jnp.asarray(np.asarray(dst, np.int64))),
           Tensor(jnp.asarray(np.asarray(order, np.int64))),
           Tensor(jnp.asarray(np.asarray([local[int(n)] for n in xs],
                                         np.int64))))
    if return_eids and ev is not None:
        res = res + (Tensor(jnp.asarray(np.asarray(es, np.int64))),)
    return res


khop_sampler = graph_khop_sampler  # python-api name


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous-edge-type reindex (reference `geometric/reindex.py`
    reindex_heter_graph): neighbors/count are PER EDGE TYPE lists sharing
    one id space; all types reindex against one mapping (x first, then
    new nodes in first-appearance order across types)."""
    import numpy as np

    from paddle_tpu.core.tensor import Tensor

    xs = _np1d(x, np.int64)
    nbs = [_np1d(n, np.int64) for n in neighbors]
    cts = [_np1d(c, np.int64) for c in count]
    mapping = {int(v): i for i, v in enumerate(xs)}
    for nb in nbs:
        for v in nb:
            if int(v) not in mapping:
                mapping[int(v)] = len(mapping)
    srcs, dsts = [], []
    for nb, ct in zip(nbs, cts):
        srcs.append(np.asarray([mapping[int(v)] for v in nb], np.int64))
        dsts.append(np.repeat(np.arange(len(xs), dtype=np.int64), ct))
    import jax.numpy as jnp

    out_nodes = np.asarray(sorted(mapping, key=mapping.get), np.int64)
    return (Tensor(jnp.asarray(np.concatenate(srcs) if srcs
                               else np.zeros(0, np.int64))),
            Tensor(jnp.asarray(np.concatenate(dsts) if dsts
                               else np.zeros(0, np.int64))),
            Tensor(jnp.asarray(out_nodes)))
