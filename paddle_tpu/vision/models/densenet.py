"""DenseNet (reference: `python/paddle/vision/models/densenet.py`)."""

import paddle_tpu as paddle
from paddle_tpu import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]


class _DenseLayer(nn.Layer):
    def __init__(self, num_input, growth_rate, bn_size):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(num_input)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(num_input, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        return paddle.concat([x, out], axis=1)


class _Transition(nn.Sequential):
    def __init__(self, num_input, num_output):
        super().__init__(
            nn.BatchNorm2D(num_input), nn.ReLU(),
            nn.Conv2D(num_input, num_output, 1, bias_attr=False),
            nn.AvgPool2D(2, stride=2),
        )


class DenseNet(nn.Layer):
    def __init__(self, layers=121, growth_rate=32, num_init_features=64,
                 bn_size=4, num_classes=1000):
        super().__init__()
        cfgs = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
                169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
                264: (6, 12, 64, 48)}
        block_config = cfgs[layers]
        feats = [
            nn.Conv2D(3, num_init_features, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(num_init_features), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
        ]
        num = num_init_features
        for i, n in enumerate(block_config):
            for _ in range(n):
                feats.append(_DenseLayer(num, growth_rate, bn_size))
                num += growth_rate
            if i != len(block_config) - 1:
                feats.append(_Transition(num, num // 2))
                num //= 2
        feats += [nn.BatchNorm2D(num), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Linear(num, num_classes)

    def forward(self, x):
        x = self.features(x)
        x = self.avgpool(x).flatten(1)
        return self.classifier(x)


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    # the 161 variant's stock widths, overridable by explicit kwargs
    kwargs.setdefault("growth_rate", 48)
    kwargs.setdefault("num_init_features", 96)
    return DenseNet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(264, **kwargs)
