"""Vision Transformer (homogeneous-block vision model; reference's vision
zoo lives in `python/paddle/vision/models/` — ViT is the TPU-friendliest
member: every FLOP is an MXU matmul, and the repeated encoder block makes it
pipeline-parallelizable through `distributed.PipelineEngine`).
"""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import nn

__all__ = ["VisionTransformer", "vit_b_16", "vit_tiny", "vit_pipeline_descs"]


class PatchEmbed(nn.Layer):
    """Image -> patch tokens (+ class token + learned position embedding)."""

    def __init__(self, image_size=224, patch_size=16, in_channels=3,
                 embed_dim=768):
        super().__init__()
        if image_size % patch_size:
            raise ValueError("patch_size must evenly divide image_size")
        self.num_patches = (image_size // patch_size) ** 2
        self.proj = nn.Conv2D(in_channels, embed_dim, kernel_size=patch_size,
                              stride=patch_size)
        self.cls_token = self.create_parameter(
            [1, 1, embed_dim], default_initializer=nn.initializer.Normal(std=0.02))
        self.pos_embed = self.create_parameter(
            [1, self.num_patches + 1, embed_dim],
            default_initializer=nn.initializer.Normal(std=0.02))

    def forward(self, x):
        b = x.shape[0]
        x = self.proj(x)                      # [b, d, h/p, w/p]
        d = x.shape[1]
        x = paddle.transpose(
            paddle.reshape(x, [b, d, -1]), [0, 2, 1])  # [b, n, d]
        cls = paddle.expand(self.cls_token, [b, 1, d])
        x = paddle.concat([cls, x], axis=1)
        return x + self.pos_embed


class ViTHead(nn.Layer):
    def __init__(self, embed_dim, num_classes):
        super().__init__()
        self.norm = nn.LayerNorm(embed_dim)
        self.head = nn.Linear(embed_dim, num_classes)

    def forward(self, x):
        return self.head(self.norm(x)[:, 0])


class VisionTransformer(nn.Layer):
    def __init__(self, image_size=224, patch_size=16, in_channels=3,
                 embed_dim=768, depth=12, num_heads=12, mlp_ratio=4.0,
                 dropout=0.0, num_classes=1000):
        super().__init__()
        self.patch_embed = PatchEmbed(image_size, patch_size, in_channels,
                                      embed_dim)
        blk = lambda: nn.TransformerEncoderLayer(  # noqa: E731
            d_model=embed_dim, nhead=num_heads,
            dim_feedforward=int(embed_dim * mlp_ratio), dropout=dropout,
            activation="gelu", normalize_before=True)
        self.blocks = nn.LayerList([blk() for _ in range(depth)])
        self.head = ViTHead(embed_dim, num_classes)

    def forward(self, x):
        x = self.patch_embed(x)
        for b in self.blocks:
            x = b(x)
        return self.head(x)


def vit_pipeline_descs(image_size=32, patch_size=4, in_channels=3,
                       embed_dim=64, depth=4, num_heads=4, mlp_ratio=4.0,
                       dropout=0.0, num_classes=10):
    """LayerDesc stack for `PipelineLayer`: [patch-embed] + depth encoder
    blocks + [cls head] — the vision counterpart of `bert_pipeline_descs`."""
    from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import LayerDesc

    descs = [PatchEmbed(image_size, patch_size, in_channels, embed_dim)]
    descs += [LayerDesc(nn.TransformerEncoderLayer, d_model=embed_dim,
                        nhead=num_heads,
                        dim_feedforward=int(embed_dim * mlp_ratio),
                        dropout=dropout, activation="gelu",
                        normalize_before=True)
              for _ in range(depth)]
    descs.append(ViTHead(embed_dim, num_classes))
    return descs


def vit_b_16(num_classes=1000, **kwargs):
    return VisionTransformer(embed_dim=768, depth=12, num_heads=12,
                             num_classes=num_classes, **kwargs)


def vit_tiny(image_size=32, patch_size=4, num_classes=10, **kwargs):
    cfg = dict(embed_dim=64, depth=4, num_heads=4, dropout=0.0)
    cfg.update(kwargs)
    return VisionTransformer(image_size=image_size, patch_size=patch_size,
                             num_classes=num_classes, **cfg)
