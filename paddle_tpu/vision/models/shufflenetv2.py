"""ShuffleNetV2 (reference: `python/paddle/vision/models/shufflenetv2.py`)."""

import paddle_tpu as paddle
from paddle_tpu import nn

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]


def _act_layer(act):
    return nn.Swish() if act == "swish" else nn.ReLU()


def channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = paddle.reshape(x, [b, groups, c // groups, h, w])
    x = paddle.transpose(x, [0, 2, 1, 3, 4])
    return paddle.reshape(x, [b, c, h, w])


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_features = oup // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride=stride, padding=1, groups=inp,
                          bias_attr=False),
                nn.BatchNorm2D(inp),
                nn.Conv2D(inp, branch_features, 1, bias_attr=False),
                nn.BatchNorm2D(branch_features), _act_layer(act),
            )
            b2_in = inp
        else:
            self.branch1 = None
            b2_in = inp // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_features, 1, bias_attr=False),
            nn.BatchNorm2D(branch_features), _act_layer(act),
            nn.Conv2D(branch_features, branch_features, 3, stride=stride,
                      padding=1, groups=branch_features, bias_attr=False),
            nn.BatchNorm2D(branch_features),
            nn.Conv2D(branch_features, branch_features, 1, bias_attr=False),
            nn.BatchNorm2D(branch_features), _act_layer(act),
        )

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000):
        super().__init__()
        if act not in ("relu", "swish"):
            raise ValueError(f"act must be relu or swish, got {act!r}")
        self.act = act
        stage_repeats = [4, 8, 4]
        channels = {0.25: [24, 24, 48, 96, 512],
                    0.33: [24, 32, 64, 128, 512],
                    0.5: [24, 48, 96, 192, 1024],
                    1.0: [24, 116, 232, 464, 1024],
                    1.5: [24, 176, 352, 704, 1024],
                    2.0: [24, 244, 488, 976, 2048]}[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, channels[0], 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(channels[0]), _act_layer(act),
        )
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        inp = channels[0]
        for repeats, oup in zip(stage_repeats, channels[1:4]):
            blocks = [InvertedResidual(inp, oup, 2, act)]
            blocks += [InvertedResidual(oup, oup, 1, act)
                       for _ in range(repeats - 1)]
            stages.append(nn.Sequential(*blocks))
            inp = oup
        self.stages = nn.Sequential(*stages)
        self.conv5 = nn.Sequential(
            nn.Conv2D(inp, channels[-1], 1, bias_attr=False),
            nn.BatchNorm2D(channels[-1]), _act_layer(act),
        )
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(channels[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.stages(x)
        x = self.conv5(x)
        x = self.avgpool(x).flatten(1)
        return self.fc(x)


def _make(scale):
    def f(pretrained=False, **kwargs):
        return ShuffleNetV2(scale, **kwargs)

    return f


shufflenet_v2_x0_25 = _make(0.25)
shufflenet_v2_x0_5 = _make(0.5)
shufflenet_v2_x1_0 = _make(1.0)
shufflenet_v2_x1_5 = _make(1.5)
shufflenet_v2_x2_0 = _make(2.0)
shufflenet_v2_x0_33 = _make(0.33)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(1.0, act="swish", **kwargs)
