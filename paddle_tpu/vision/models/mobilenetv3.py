"""MobileNetV3 Large/Small (reference `python/paddle/vision/models/
mobilenetv3.py`): inverted residuals with squeeze-excite and
hardswish."""

from paddle_tpu import nn

__all__ = ["MobileNetV3Large", "MobileNetV3Small", "mobilenet_v3_large",
           "mobilenet_v3_small"]


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SqueezeExcite(nn.Layer):
    def __init__(self, c):
        super().__init__()
        mid = _make_divisible(c // 4)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, mid, 1)
        self.fc2 = nn.Conv2D(mid, c, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        act_layer = nn.Hardswish if act == "hardswish" else nn.ReLU
        layers = []
        if exp_c != in_c:
            layers += [nn.Conv2D(in_c, exp_c, 1, bias_attr=False),
                       nn.BatchNorm2D(exp_c), act_layer()]
        layers += [nn.Conv2D(exp_c, exp_c, k, stride=stride,
                             padding=k // 2, groups=exp_c, bias_attr=False),
                   nn.BatchNorm2D(exp_c)]
        if use_se:
            layers.append(_SqueezeExcite(exp_c))
        layers += [act_layer(),
                   nn.Conv2D(exp_c, out_c, 1, bias_attr=False),
                   nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class _MobileNetV3(nn.Layer):
    # cfg rows: (kernel, exp, out, use_se, act, stride)
    CFG = []
    LAST_EXP = 0

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        first_c = _make_divisible(16 * scale)
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, first_c, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(first_c), nn.Hardswish(),
        )
        blocks = []
        in_c = first_c
        for k, exp, out, se, act, s in self.CFG:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            blocks.append(_InvertedResidual(in_c, exp_c, out_c, k, s, se,
                                            act))
            in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        last_exp = _make_divisible(self.LAST_EXP * scale)
        self.conv2 = nn.Sequential(
            nn.Conv2D(in_c, last_exp, 1, bias_attr=False),
            nn.BatchNorm2D(last_exp), nn.Hardswish(),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            last_c = _make_divisible(last_exp * 1.25)
            self.classifier = nn.Sequential(
                nn.Linear(last_exp, last_c), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.conv2(self.blocks(self.conv1(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Large(_MobileNetV3):
    CFG = [
        (3, 16, 16, False, "relu", 1),
        (3, 64, 24, False, "relu", 2),
        (3, 72, 24, False, "relu", 1),
        (5, 72, 40, True, "relu", 2),
        (5, 120, 40, True, "relu", 1),
        (5, 120, 40, True, "relu", 1),
        (3, 240, 80, False, "hardswish", 2),
        (3, 200, 80, False, "hardswish", 1),
        (3, 184, 80, False, "hardswish", 1),
        (3, 184, 80, False, "hardswish", 1),
        (3, 480, 112, True, "hardswish", 1),
        (3, 672, 112, True, "hardswish", 1),
        (5, 672, 160, True, "hardswish", 2),
        (5, 960, 160, True, "hardswish", 1),
        (5, 960, 160, True, "hardswish", 1),
    ]
    LAST_EXP = 960


class MobileNetV3Small(_MobileNetV3):
    CFG = [
        (3, 16, 16, True, "relu", 2),
        (3, 72, 24, False, "relu", 2),
        (3, 88, 24, False, "relu", 1),
        (5, 96, 40, True, "hardswish", 2),
        (5, 240, 40, True, "hardswish", 1),
        (5, 240, 40, True, "hardswish", 1),
        (5, 120, 48, True, "hardswish", 1),
        (5, 144, 48, True, "hardswish", 1),
        (5, 288, 96, True, "hardswish", 2),
        (5, 576, 96, True, "hardswish", 1),
        (5, 576, 96, True, "hardswish", 1),
    ]
    LAST_EXP = 576


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)
