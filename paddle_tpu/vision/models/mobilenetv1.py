"""MobileNetV1 (reference `python/paddle/vision/models/mobilenetv1.py`):
13 depthwise-separable blocks. Depthwise convs map to XLA's grouped
convolution; at groups == channels XLA lowers them to per-channel
contractions on the VPU, so no special kernel is needed."""

from paddle_tpu import nn

__all__ = ["MobileNetV1", "mobilenet_v1"]


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, mid_c, out_c, stride, scale):
        super().__init__()
        in_c, mid_c, out_c = (int(c * scale) for c in (in_c, mid_c, out_c))
        self.dw = nn.Sequential(
            nn.Conv2D(in_c, mid_c, 3, stride=stride, padding=1,
                      groups=in_c, bias_attr=False),
            nn.BatchNorm2D(mid_c), nn.ReLU(),
        )
        self.pw = nn.Sequential(
            nn.Conv2D(mid_c, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c), nn.ReLU(),
        )

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, int(32 * scale), 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(int(32 * scale)), nn.ReLU(),
        )
        # (in, mid, out, stride) per reference block list
        cfg = [(32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
               (128, 128, 256, 2), (256, 256, 256, 1), (256, 256, 512, 2),
               (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 512, 1),
               (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 1024, 2),
               (1024, 1024, 1024, 1)]
        self.blocks = nn.Sequential(*[
            _DepthwiseSeparable(i, m, o, s, scale) for i, m, o, s in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
