"""Vision datasets (reference: `python/paddle/vision/datasets/mnist.py:41`,
`cifar.py`). With no network egress, datasets load from local files when
present (same idx/pickle formats as the reference) and otherwise fall back to
a deterministic synthetic sample so training loops stay runnable."""

import gzip
import os
import struct

import numpy as np

from paddle_tpu.io import Dataset


class MNIST(Dataset):
    """reference: `python/paddle/vision/datasets/mnist.py:41`"""

    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path, mode)

    def _load(self, image_path, label_path, mode):
        if image_path and label_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), np.uint8)
            return images.astype(np.float32) / 255.0, labels.astype(np.int64)
        # synthetic fallback: class-dependent patterns, deterministic
        n = 2048 if mode == "train" else 512
        rng = np.random.RandomState(0 if mode == "train" else 1)
        labels = rng.randint(0, 10, n).astype(np.int64)
        images = rng.rand(n, 28, 28).astype(np.float32) * 0.1
        for i, l in enumerate(labels):
            images[i, (l * 2):(l * 2 + 6), 4:24] += 0.8  # class-coded stripe
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx][None]  # [1, 28, 28]
        label = np.asarray([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """reference: `python/paddle/vision/datasets/cifar.py`"""

    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend=None):
        self.transform = transform
        n = 2048 if mode == "train" else 512
        rng = np.random.RandomState(2 if mode == "train" else 3)
        self.labels = rng.randint(0, self.NUM_CLASSES, n).astype(np.int64)
        self.images = rng.rand(n, 3, 32, 32).astype(np.float32) * 0.1
        for i, l in enumerate(self.labels):
            self.images[i, l % 3, (l * 3):(l * 3 + 2), :] += 0.9

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class ImageFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.samples = []
        self.transform = transform
        if os.path.isdir(root):
            for dirpath, _, files in os.walk(root):
                for f in sorted(files):
                    self.samples.append(os.path.join(dirpath, f))

    def __getitem__(self, idx):
        path = self.samples[idx]
        img = np.asarray(_load_image(path))
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.classes = sorted(d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))) if os.path.isdir(root) else []
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        self.samples = []
        self.transform = transform
        for c in self.classes:
            cdir = os.path.join(root, c)
            for f in sorted(os.listdir(cdir)):
                self.samples.append((os.path.join(cdir, f), self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = np.asarray(_load_image(path))
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


def _load_image(path):
    try:
        from PIL import Image

        return Image.open(path).convert("RGB")
    except Exception:
        return np.zeros((32, 32, 3), np.uint8)


class Flowers(Dataset):
    """reference vision/datasets/flowers.py (Oxford 102 flowers).
    Synthetic stand-in (zero-egress image): class-coded color fields at
    the real 3xHxW shape and 102-class label space."""

    N_CLASSES = 102

    def __init__(self, mode="train", transform=None, backend=None,
                 image_size=64, n_items=128):
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = n_items if mode == "train" else max(16, n_items // 4)
        self.labels = rng.integers(0, self.N_CLASSES, n).astype("int64")
        hue = (self.labels[:, None, None, None] / self.N_CLASSES)
        base = rng.random((n, 3, image_size, image_size)).astype("float32")
        self.images = (0.5 * base + 0.5 * hue).astype("float32")
        self.transform = transform

    def __getitem__(self, idx):
        img, lab = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, lab

    def __len__(self):
        return len(self.labels)


class VOC2012(Dataset):
    """reference vision/datasets/voc2012.py: segmentation pairs
    (image, mask). Synthetic stand-in: images with a class-coded
    rectangle and the matching 21-class mask."""

    N_CLASSES = 21

    def __init__(self, mode="train", transform=None, backend=None,
                 image_size=64, n_items=64):
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = n_items if mode == "train" else max(8, n_items // 4)
        s = image_size
        self.images = rng.random((n, 3, s, s)).astype("float32") * 0.3
        self.masks = np.zeros((n, s, s), "int64")
        for i in range(n):
            cls = int(rng.integers(1, self.N_CLASSES))
            x0, y0 = rng.integers(0, s // 2, 2)
            h, w = rng.integers(s // 4, s // 2, 2)
            self.images[i, :, y0:y0 + h, x0:x0 + w] += cls / self.N_CLASSES
            self.masks[i, y0:y0 + h, x0:x0 + w] = cls
        self.transform = transform

    def __getitem__(self, idx):
        img, mask = self.images[idx], self.masks[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self.images)
