"""Functional vision transforms (reference `python/paddle/vision/
transforms/functional{,_pil,_cv2,_tensor}.py`). One numpy implementation
instead of the reference's three backends: inputs may be PIL images,
numpy arrays (HWC or CHW), or Tensors; output matches the input family
(PIL -> PIL, Tensor -> Tensor, ndarray -> ndarray). Geometric ops use an
inverse-map bilinear warp — the same sampling the reference's cv2 branch
does — vectorized in numpy (host-side preprocessing; the TPU never sees
these)."""

from __future__ import annotations

import math
import numbers

import numpy as np

__all__ = [
    "to_tensor", "normalize", "resize", "hflip", "vflip", "crop",
    "center_crop", "pad", "adjust_brightness", "adjust_contrast",
    "adjust_hue", "adjust_saturation", "to_grayscale", "rotate", "affine",
    "perspective", "erase",
]


def _unwrap(img):
    """-> (hwc float-preserving ndarray, restore_fn)."""
    try:
        from PIL import Image

        if isinstance(Image, type(None)):
            pass
    except ImportError:
        Image = None
    from paddle_tpu.core.tensor import Tensor

    if Image is not None and hasattr(img, "convert") and hasattr(img, "size"):
        arr = np.asarray(img)
        mode = img.mode

        def restore(a):
            from PIL import Image as I

            return I.fromarray(np.clip(a, 0, 255).astype(np.uint8), mode)

        return arr, restore
    if isinstance(img, Tensor):
        arr = img.numpy()
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4) \
            and arr.shape[-1] not in (1, 3, 4)
        if chw:
            arr = arr.transpose(1, 2, 0)

            def restore(a):
                import paddle_tpu as paddle

                return paddle.to_tensor(
                    np.ascontiguousarray(a.transpose(2, 0, 1)))
        else:
            def restore(a):
                import paddle_tpu as paddle

                return paddle.to_tensor(np.ascontiguousarray(a))

        return arr, restore
    arr = np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4) \
        and arr.shape[-1] not in (1, 3, 4)
    if chw:
        arr = arr.transpose(1, 2, 0)
        return arr, lambda a: np.ascontiguousarray(
            a.transpose(2, 0, 1)).astype(np.asarray(img).dtype, copy=False)
    return arr, lambda a: a.astype(arr.dtype, copy=False) \
        if np.issubdtype(arr.dtype, np.integer) else a


def _clip_like(a, ref_dtype):
    if np.issubdtype(ref_dtype, np.integer):
        return np.clip(a, 0, 255)
    return a


# -- already-present wrappers re-exported for the functional namespace ------

def to_tensor(pic, data_format="CHW"):
    from paddle_tpu.vision.transforms import ToTensor

    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from paddle_tpu.vision.transforms import Normalize

    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    from paddle_tpu.vision.transforms import Resize

    return Resize(size, interpolation)(img)


def hflip(img):
    arr, restore = _unwrap(img)
    return restore(arr[:, ::-1].copy())


def vflip(img):
    arr, restore = _unwrap(img)
    return restore(arr[::-1].copy())


def crop(img, top, left, height, width):
    arr, restore = _unwrap(img)
    return restore(arr[top:top + height, left:left + width].copy())


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr, restore = _unwrap(img)
    h, w = arr.shape[:2]
    th, tw = output_size
    return restore(arr[max((h - th) // 2, 0):max((h - th) // 2, 0) + th,
                       max((w - tw) // 2, 0):max((w - tw) // 2, 0) + tw]
                   .copy())


_PAD_MODES = {"constant": "constant", "edge": "edge",
              "reflect": "reflect", "symmetric": "symmetric"}


def pad(img, padding, fill=0, padding_mode="constant"):
    arr, restore = _unwrap(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    widths = [(pt, pb), (pl, pr)] + [(0, 0)] * (arr.ndim - 2)
    mode = _PAD_MODES[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return restore(np.pad(arr, widths, mode=mode, **kw))


def adjust_brightness(img, brightness_factor):
    arr, restore = _unwrap(img)
    out = arr.astype(np.float32) * brightness_factor
    return restore(_clip_like(out, arr.dtype))


def adjust_contrast(img, contrast_factor):
    arr, restore = _unwrap(img)
    f = arr.astype(np.float32)
    gray = f.mean() if f.ndim == 2 else (
        f[..., :3] @ np.array([0.299, 0.587, 0.114], np.float32)).mean()
    out = gray * (1 - contrast_factor) + f * contrast_factor
    return restore(_clip_like(out, arr.dtype))


def adjust_saturation(img, saturation_factor):
    arr, restore = _unwrap(img)
    f = arr.astype(np.float32)
    if f.ndim == 2:
        return restore(arr)
    gray = f[..., :3] @ np.array([0.299, 0.587, 0.114], np.float32)
    out = f.copy()
    out[..., :3] = (gray[..., None] * (1 - saturation_factor)
                    + f[..., :3] * saturation_factor)
    return restore(_clip_like(out, arr.dtype))


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns), via vectorized
    RGB<->HSV (reference functional_tensor.adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr, restore = _unwrap(img)
    f = arr.astype(np.float32)
    if f.ndim == 2:
        return restore(arr)
    scale = 255.0 if np.issubdtype(arr.dtype, np.integer) else 1.0
    rgb = f[..., :3] / scale
    mx = rgb.max(-1)
    mn = rgb.min(-1)
    diff = mx - mn
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    safe = np.where(diff == 0, 1.0, diff)
    h = np.select(
        [mx == r, mx == g],
        [((g - b) / safe) % 6.0, (b - r) / safe + 2.0],
        default=(r - g) / safe + 4.0) / 6.0
    h = np.where(diff == 0, 0.0, h)
    s = np.where(mx == 0, 0.0, diff / np.where(mx == 0, 1.0, mx))
    v = mx
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    fr = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - fr * s)
    t = v * (1 - (1 - fr) * s)
    i = i.astype(np.int32) % 6
    out = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q]),
    ], axis=-1) * scale
    res = f.copy()
    res[..., :3] = out
    return restore(_clip_like(res, arr.dtype))


def to_grayscale(img, num_output_channels=1):
    arr, restore = _unwrap(img)
    f = arr.astype(np.float32)
    if f.ndim == 2:
        gray = f
    else:
        gray = f[..., :3] @ np.array([0.299, 0.587, 0.114], np.float32)
    out = np.repeat(gray[..., None], num_output_channels, axis=-1) \
        if num_output_channels > 1 else gray[..., None] \
        if arr.ndim == 3 else gray
    return restore(_clip_like(out, arr.dtype))


def _warp(arr, inv_matrix, fill=0.0):
    """Bilinear inverse warp: out[y, x] = in @ inv_matrix*(x, y, 1)."""
    h, w = arr.shape[:2]
    ys, xs = np.mgrid[0:h, 0:w].astype(np.float32)
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], axis=-1) @ np.asarray(
        inv_matrix, np.float32).T        # [h, w, 3]
    denom = coords[..., 2]
    sx = coords[..., 0] / np.where(denom == 0, 1.0, denom)
    sy = coords[..., 1] / np.where(denom == 0, 1.0, denom)
    x0 = np.floor(sx).astype(np.int32)
    y0 = np.floor(sy).astype(np.int32)
    wx = sx - x0
    wy = sy - y0
    valid = (sx >= -1) & (sx <= w) & (sy >= -1) & (sy <= h)

    def sample(yi, xi):
        inside = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        xi_c = np.clip(xi, 0, w - 1)
        yi_c = np.clip(yi, 0, h - 1)
        v = arr[yi_c, xi_c].astype(np.float32)
        m = inside.astype(np.float32)
        return v * (m[..., None] if arr.ndim == 3 else m)

    wxe = wx[..., None] if arr.ndim == 3 else wx
    wye = wy[..., None] if arr.ndim == 3 else wy
    out = (sample(y0, x0) * (1 - wxe) * (1 - wye)
           + sample(y0, x0 + 1) * wxe * (1 - wye)
           + sample(y0 + 1, x0) * (1 - wxe) * wye
           + sample(y0 + 1, x0 + 1) * wxe * wye)
    if fill:
        ve = valid[..., None] if arr.ndim == 3 else valid
        out = np.where(ve, out, np.float32(fill))
    return out


def _affine_inv(angle, translate, scale, shear, center):
    """Inverse of the output->input affine map the reference composes
    (functional.affine: rot(angle) @ shear @ scale about center, then
    translate)."""
    rot = math.radians(angle)
    sx, sy = (math.radians(s) for s in shear)
    cx, cy = center
    tx, ty = translate
    # forward matrix M (input->output), reference cv2 convention
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    M = np.array([[scale * a, scale * b,
                   cx + tx - scale * (a * cx + b * cy)],
                  [scale * c, scale * d,
                   cy + ty - scale * (c * cx + d * cy)],
                  [0, 0, 1]], np.float32)
    return np.linalg.inv(M)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr, restore = _unwrap(img)
    h, w = arr.shape[:2]
    c = center or ((w - 1) * 0.5, (h - 1) * 0.5)
    inv = _affine_inv(-angle, (0, 0), 1.0, (0.0, 0.0), c)
    if expand:
        rad = math.radians(angle)
        nw = int(abs(w * math.cos(rad)) + abs(h * math.sin(rad)) + 0.5)
        nh = int(abs(h * math.cos(rad)) + abs(w * math.sin(rad)) + 0.5)
        shift = np.array([[1, 0, (w - nw) * 0.5], [0, 1, (h - nh) * 0.5],
                          [0, 0, 1]], np.float32)
        inv = inv @ shift
        padded = np.zeros((nh, nw) + arr.shape[2:], arr.dtype)
        out = _warp_into(arr, padded.shape[:2], inv, fill)
        return restore(_clip_like(out, arr.dtype))
    out = _warp(arr, inv, fill)
    return restore(_clip_like(out, arr.dtype))


def _warp_into(arr, out_hw, inv_matrix, fill=0.0):
    h, w = arr.shape[:2]
    oh, ow = out_hw
    ys, xs = np.mgrid[0:oh, 0:ow].astype(np.float32)
    coords = np.stack([xs, ys, np.ones_like(xs)], axis=-1) @ np.asarray(
        inv_matrix, np.float32).T
    sx = coords[..., 0]
    sy = coords[..., 1]
    x0 = np.floor(sx).astype(np.int32)
    y0 = np.floor(sy).astype(np.int32)
    wx = sx - x0
    wy = sy - y0

    def sample(yi, xi):
        inside = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        v = arr[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)].astype(
            np.float32)
        m = inside.astype(np.float32)
        return v * (m[..., None] if arr.ndim == 3 else m)

    wxe = wx[..., None] if arr.ndim == 3 else wx
    wye = wy[..., None] if arr.ndim == 3 else wy
    return (sample(y0, x0) * (1 - wxe) * (1 - wye)
            + sample(y0, x0 + 1) * wxe * (1 - wye)
            + sample(y0 + 1, x0) * (1 - wxe) * wye
            + sample(y0 + 1, x0 + 1) * wxe * wye)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    arr, restore = _unwrap(img)
    h, w = arr.shape[:2]
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    c = center or ((w - 1) * 0.5, (h - 1) * 0.5)
    inv = _affine_inv(angle, tuple(translate), scale, tuple(shear), c)
    return restore(_clip_like(_warp(arr, inv, fill), arr.dtype))


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Warp so that startpoints map to endpoints (reference
    functional.perspective): solve the 8-dof homography, then inverse
    sample."""
    arr, restore = _unwrap(img)
    A = []
    bv = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        A.append([sx, sy, 1, 0, 0, 0, -ex * sx, -ex * sy])
        bv.append(ex)
        A.append([0, 0, 0, sx, sy, 1, -ey * sx, -ey * sy])
        bv.append(ey)
    coeff = np.linalg.solve(np.asarray(A, np.float64),
                            np.asarray(bv, np.float64))
    H = np.append(coeff, 1.0).reshape(3, 3).astype(np.float32)
    inv = np.linalg.inv(H)
    return restore(_clip_like(_warp(arr, inv, fill), arr.dtype))


def erase(img, i, j, h, w, v, inplace=False):
    """Fill img[..., i:i+h, j:j+w] with v (reference functional.erase;
    Tensor path is CHW)."""
    from paddle_tpu.core.tensor import Tensor

    if isinstance(img, Tensor):
        arr = img.numpy() if not inplace else img.numpy()
        chw = arr.ndim == 3
        val = np.broadcast_to(np.asarray(v, arr.dtype),
                              (arr.shape[0], h, w) if chw else (h, w))
        out = arr.copy()
        if chw:
            out[:, i:i + h, j:j + w] = val
        else:
            out[i:i + h, j:j + w] = val
        import paddle_tpu as paddle

        res = paddle.to_tensor(out)
        if inplace:
            img._refill(res._data)
            return img
        return res
    arr, restore = _unwrap(img)
    out = arr.copy()
    out[i:i + h, j:j + w] = np.asarray(v, out.dtype)
    return restore(out)
