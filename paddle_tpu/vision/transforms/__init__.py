"""Vision transforms over numpy arrays (reference: `python/paddle/vision/transforms/`)."""

import numbers

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = [-1] + [1] * (arr.ndim - 1)
        else:
            shape = [1] * (arr.ndim - 1) + [-1]
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        import jax

        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + ((arr.shape[-1],) if arr.ndim == 3 else ())
        return np.asarray(jax.image.resize(arr, out_shape, "linear"))


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i, j = max((h - th) // 2, 0), max((w - tw) // 2, 0)
        return arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        return arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            return arr[..., ::-1].copy()
        return arr


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if np.random.rand() < self.prob:
            return (arr[:, ::-1] if chw else arr[::-1]).copy()
        return arr


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
