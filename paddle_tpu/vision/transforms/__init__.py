"""Vision transforms over numpy arrays (reference: `python/paddle/vision/transforms/`)."""

import math
import numbers

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = [-1] + [1] * (arr.ndim - 1)
        else:
            shape = [1] * (arr.ndim - 1) + [-1]
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        import jax

        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + ((arr.shape[-1],) if arr.ndim == 3 else ())
        return np.asarray(jax.image.resize(arr, out_shape, "linear"))


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i, j = max((h - th) // 2, 0), max((w - tw) // 2, 0)
        return arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        return arr[:, i:i + th, j:j + tw] if chw else arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            return arr[..., ::-1].copy()
        return arr


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if np.random.rand() < self.prob:
            return (arr[:, ::-1] if chw else arr[::-1]).copy()
        return arr


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


# -- r5 final sweep: the rest of the reference transforms surface
#    (reference python/paddle/vision/transforms/transforms.py) --------------

from paddle_tpu.vision.transforms import functional as F  # noqa: E402
from paddle_tpu.vision.transforms.functional import (  # noqa: E402,F401
    adjust_brightness, adjust_contrast, adjust_hue, affine, center_crop,
    crop, erase, hflip, pad, perspective, rotate, to_grayscale, vflip,
)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        # float v means jitter in [max(0, 1-v), 1+v]; an explicit
        # (lo, hi) tuple is passed through (reference _check_input)
        if isinstance(value, (list, tuple)):
            lo, hi = value
        else:
            if value < 0:
                raise ValueError("brightness value should be non-negative")
            lo, hi = max(0.0, 1 - value), 1 + value
        if lo > hi or lo < 0:
            raise ValueError(f"invalid brightness range {(lo, hi)}")
        self.value = (float(lo), float(hi))

    def _apply_image(self, img):
        lo, hi = self.value
        if lo == hi == 1.0:
            return img
        return F.adjust_brightness(img, np.random.uniform(lo, hi))


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        # float v means jitter in [max(0, 1-v), 1+v]; an explicit
        # (lo, hi) tuple is passed through (reference _check_input)
        if isinstance(value, (list, tuple)):
            lo, hi = value
        else:
            if value < 0:
                raise ValueError("contrast value should be non-negative")
            lo, hi = max(0.0, 1 - value), 1 + value
        if lo > hi or lo < 0:
            raise ValueError(f"invalid contrast range {(lo, hi)}")
        self.value = (float(lo), float(hi))

    def _apply_image(self, img):
        lo, hi = self.value
        if lo == hi == 1.0:
            return img
        return F.adjust_contrast(img, np.random.uniform(lo, hi))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        # float v means jitter in [max(0, 1-v), 1+v]; an explicit
        # (lo, hi) tuple is passed through (reference _check_input)
        if isinstance(value, (list, tuple)):
            lo, hi = value
        else:
            if value < 0:
                raise ValueError("saturation value should be non-negative")
            lo, hi = max(0.0, 1 - value), 1 + value
        if lo > hi or lo < 0:
            raise ValueError(f"invalid saturation range {(lo, hi)}")
        self.value = (float(lo), float(hi))

    def _apply_image(self, img):
        lo, hi = self.value
        if lo == hi == 1.0:
            return img
        return F.adjust_saturation(img, np.random.uniform(lo, hi))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return F.adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Apply brightness/contrast/saturation/hue jitter in random order
    (reference transforms.ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for idx in order:
            img = self.transforms[idx]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            degrees = (-degrees, degrees)
        self.degrees = tuple(degrees)
        self.expand, self.center, self.fill = expand, center, fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return F.rotate(img, angle, expand=self.expand, center=self.center,
                        fill=self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = tuple(degrees)
        self.translate, self.scale_rng, self.shear = translate, scale, shear
        self.fill, self.center = fill, center

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = (arr.shape[:2] if arr.ndim == 2 or arr.shape[-1] in (1, 3, 4)
                else arr.shape[1:3])
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        scale = (np.random.uniform(*self.scale_rng)
                 if self.scale_rng is not None else 1.0)
        shear = (0.0, 0.0)
        if self.shear is not None:
            sh = self.shear
            if isinstance(sh, numbers.Number):
                sh = (-sh, sh)
            shear = (np.random.uniform(sh[0], sh[1]),
                     np.random.uniform(sh[2], sh[3]) if len(sh) == 4 else 0.0)
        return F.affine(img, angle, (tx, ty), scale, shear, fill=self.fill,
                        center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob, self.distortion_scale, self.fill = (
            prob, distortion_scale, fill)

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img)
        h, w = arr.shape[:2] if (arr.ndim == 2 or arr.shape[-1] in (1, 3, 4)) \
            else arr.shape[1:3]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1),
                np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1)),
               (np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1))]
        return F.perspective(img, start, end, fill=self.fill)


class RandomResizedCrop(BaseTransform):
    """Crop a random area/aspect patch and resize it (reference
    transforms.RandomResizedCrop — the ImageNet training crop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4) \
            and arr.shape[-1] not in (1, 3, 4)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = math.exp(np.random.uniform(math.log(self.ratio[0]),
                                            math.log(self.ratio[1])))
            cw = int(round(math.sqrt(target * ar)))
            ch = int(round(math.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                break
        else:
            cw, ch = min(w, h), min(w, h)
            i, j = (h - ch) // 2, (w - cw) // 2
        patch = arr[:, i:i + ch, j:j + cw] if chw \
            else arr[i:i + ch, j:j + cw]
        return Resize(self.size, self.interpolation)(patch)


class RandomErasing(BaseTransform):
    """Randomly blank a rectangle (reference transforms.RandomErasing;
    Zhong et al. 2017)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img if not hasattr(img, "numpy") else img.numpy())
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4) \
            and arr.shape[-1] not in (1, 3, 4)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(math.sqrt(target / ar)))
            ew = int(round(math.sqrt(target * ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                if self.value == "random":
                    vshape = ((arr.shape[0], eh, ew) if chw
                              else (eh, ew) + ((arr.shape[2],)
                                               if arr.ndim == 3 else ()))
                    v = np.random.standard_normal(vshape).astype(np.float32)
                else:
                    v = self.value
                if chw:
                    out = arr.copy()
                    out[:, i:i + eh, j:j + ew] = v
                    return out
                return F.erase(img, i, j, eh, ew, v, inplace=self.inplace)
        return img

