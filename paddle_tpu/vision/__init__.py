from paddle_tpu.vision import datasets, models, ops, transforms  # noqa: F401

# -- r5 final sweep: image backend selection (reference
#    python/paddle/vision/image.py) ------------------------------------------

_image_backend = "pil"


def set_image_backend(backend):
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend 'pil', 'cv2' or 'tensor', got {backend!r}")
    if backend == "cv2":
        raise ValueError(
            "cv2 is not available in this image; use 'pil' or 'tensor'")
    global _image_backend
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """reference vision/image.py image_load: PIL image (or HWC tensor
    with backend='tensor')."""
    from PIL import Image

    backend = backend or _image_backend
    img = Image.open(path)
    if backend == "tensor":
        import numpy as np

        import paddle_tpu as paddle

        return paddle.to_tensor(np.asarray(img))
    return img
