"""paddle.vision.ops (reference: `python/paddle/vision/ops.py` — detection
primitives backed by `paddle/phi/kernels/*/nms_kernel.*`,
`roi_align_kernel.*`, `box_coder_kernel.*`, `prior_box_kernel.*`).

TPU-native notes: roi_align is a batched bilinear gather (vectorizes
cleanly); nms is an O(n^2) suppression matrix + lax.fori greedy sweep —
static shapes, no host round trip, fine at detection-head sizes (n <= a few
thousand); box_coder/prior_box are pure elementwise math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor, apply

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "prior_box",
           "box_area", "box_iou", "distribute_fpn_proposals"]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def box_area(boxes):
    b = _data(boxes)
    return Tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def _iou_matrix(a, b):
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter, 1e-10)


def box_iou(boxes1, boxes2):
    return Tensor(_iou_matrix(_data(boxes1), _data(boxes2)))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS -> kept indices sorted by score (reference ops.yaml nms /
    vision/ops.py:nms). Category-aware when category_idxs is given (boxes
    of different categories never suppress each other)."""
    b = _data(boxes)
    n = b.shape[0]
    s = (_data(scores) if scores is not None
         else jnp.arange(n, 0, -1, dtype=jnp.float32))
    order = jnp.argsort(-s)
    b_sorted = b[order]
    iou = _iou_matrix(b_sorted, b_sorted)
    if category_idxs is not None:
        c = _data(category_idxs)[order]
        same = c[:, None] == c[None, :]
        iou = jnp.where(same, iou, 0.0)

    idx = jnp.arange(n)

    def body(i, keep):
        # box i (in score order) survives unless a higher-scored SURVIVOR
        # overlaps it beyond the threshold
        sup = jnp.any((idx < i) & (iou[i] > iou_threshold) & keep)
        return keep.at[i].set(~sup)

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # kept indices have data-dependent count: finalize on host (the
    # reference kernel also returns a dynamic-size index tensor)
    keep_np = np.asarray(jax.device_get(keep))
    order_np = np.asarray(jax.device_get(order))
    out = order_np[keep_np]
    if top_k is not None:
        out = out[:top_k]
    return Tensor(jnp.asarray(out.astype(np.int64)))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference roi_align_kernel): x [N,C,H,W]; boxes [R,4]
    (x1,y1,x2,y2 in input coords); boxes_num [N] rois per image ->
    [R, C, oh, ow]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    bx = _data(boxes).astype(jnp.float32)
    bn = np.asarray(jax.device_get(_data(boxes_num)))
    img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    offset = 0.5 if aligned else 0.0

    # sampling_ratio <= 0: per-RoI adaptive count ceil(roi_h / pooled_h)
    # like the reference roi_align_kernel; the grid buffer is statically
    # sized to the LARGEST RoI's count (capped at 8 per bin dim so one
    # whole-image box cannot inflate every RoI's grid to OOM scale — beyond
    # ~8 samples/bin the bin mean has converged) and smaller RoIs mask the
    # tail slots.
    _ADAPTIVE_CAP = 8
    if sampling_ratio > 0:
        Ry = Rx = int(sampling_ratio)
    else:
        bhost = np.asarray(jax.device_get(bx), np.float32)
        rh_all = np.maximum((bhost[:, 3] - bhost[:, 1]) * spatial_scale, 1e-3)
        rw_all = np.maximum((bhost[:, 2] - bhost[:, 0]) * spatial_scale, 1e-3)
        Ry = max(1, int(np.ceil(rh_all.max() / oh))) if len(bhost) else 1
        Rx = max(1, int(np.ceil(rw_all.max() / ow))) if len(bhost) else 1
        Ry, Rx = min(Ry, _ADAPTIVE_CAP), min(Rx, _ADAPTIVE_CAP)

    def fn(xd):
        n, c, h, w = xd.shape

        def one_roi(roi, img):
            x1, y1, x2, y2 = roi * spatial_scale - offset
            rw = jnp.maximum(x2 - x1, 1e-3)
            rh = jnp.maximum(y2 - y1, 1e-3)
            bin_w, bin_h = rw / ow, rh / oh
            if sampling_ratio > 0:
                ry = jnp.asarray(Ry, jnp.float32)
                rx = jnp.asarray(Rx, jnp.float32)
            else:
                ry = jnp.clip(jnp.ceil(rh / oh), 1, Ry)
                rx = jnp.clip(jnp.ceil(rw / ow), 1, Rx)
            ky = jnp.arange(Ry, dtype=jnp.float32)
            kx = jnp.arange(Rx, dtype=jnp.float32)
            my = (ky < ry).astype(jnp.float32)  # active sample slots
            mx = (kx < rx).astype(jnp.float32)
            gy = (y1 + (jnp.arange(oh)[:, None] + (ky[None, :] + 0.5) / ry)
                  * bin_h)
            gx = (x1 + (jnp.arange(ow)[:, None] + (kx[None, :] + 0.5) / rx)
                  * bin_w)
            gy = gy.reshape(-1)  # [oh*Ry]
            gx = gx.reshape(-1)  # [ow*Rx]
            img_feat = xd[img]  # [C, H, W]

            def bilinear(yy, xx):
                # reference zeroes samples with y < -1 or y > H (outside the
                # feature map beyond the half-pixel border) instead of
                # border-clamping them
                vy = ((yy >= -1) & (yy <= h)).astype(jnp.float32)
                vx = ((xx >= -1) & (xx <= w)).astype(jnp.float32)
                y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
                x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
                y1_ = jnp.clip(y0 + 1, 0, h - 1)
                x1_ = jnp.clip(x0 + 1, 0, w - 1)
                wy = jnp.clip(yy - y0, 0, 1)
                wx = jnp.clip(xx - x0, 0, 1)
                y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
                y1i, x1i = y1_.astype(jnp.int32), x1_.astype(jnp.int32)
                v = (img_feat[:, y0i[:, None], x0i[None, :]] * ((1 - wy)[:, None] * (1 - wx)[None, :])
                     + img_feat[:, y0i[:, None], x1i[None, :]] * ((1 - wy)[:, None] * wx[None, :])
                     + img_feat[:, y1i[:, None], x0i[None, :]] * (wy[:, None] * (1 - wx)[None, :])
                     + img_feat[:, y1i[:, None], x1i[None, :]] * (wy[:, None] * wx[None, :]))
                return v * (vy[:, None] * vx[None, :])  # [C, len(yy), len(xx)]

            vals = bilinear(gy, gx)  # [C, oh*Ry, ow*Rx]
            vals = vals.reshape(c, oh, Ry, ow, Rx)
            vals = vals * my[None, None, :, None, None] \
                * mx[None, None, None, None, :]
            return vals.sum(axis=(2, 4)) / (ry * rx)

        return jax.vmap(one_roi)(bx, img_of_roi).astype(xd.dtype)

    return apply(fn, x, _name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool RoI (reference roi_pool_kernel): TRUE max over every pixel
    whose coordinates fall in a bin (sparse sampling can miss the max), via
    per-bin masks reduced over H,W — XLA fuses the where+max so the
    [oh,ow,H,W] mask never materializes against the channel dim."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bx = _data(boxes).astype(jnp.float32)
    bn = np.asarray(jax.device_get(_data(boxes_num)))
    img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    def fn(xd):
        n, c, h, w = xd.shape
        ygrid = jnp.arange(h, dtype=jnp.float32)
        xgrid = jnp.arange(w, dtype=jnp.float32)

        def one_roi(roi, img):
            x1, y1, x2, y2 = jnp.round(roi * spatial_scale)
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            # bin boundaries (reference: floor/ceil of fractional edges)
            ys0 = y1 + jnp.floor(jnp.arange(oh) * rh / oh)
            ys1 = y1 + jnp.ceil((jnp.arange(oh) + 1) * rh / oh)
            xs0 = x1 + jnp.floor(jnp.arange(ow) * rw / ow)
            xs1 = x1 + jnp.ceil((jnp.arange(ow) + 1) * rw / ow)
            my = ((ygrid[None, :] >= ys0[:, None])
                  & (ygrid[None, :] < ys1[:, None]))   # [oh, H]
            mx = ((xgrid[None, :] >= xs0[:, None])
                  & (xgrid[None, :] < xs1[:, None]))   # [ow, W]
            mask = my[:, None, :, None] & mx[None, :, None, :]  # [oh,ow,H,W]
            feat = xd[img][None, None].astype(jnp.float32)  # [1,1,C,H,W]
            vals = jnp.where(mask[:, :, None], feat, -jnp.inf)
            out = vals.max(axis=(-2, -1))  # [oh, ow, C]
            out = jnp.where(jnp.isfinite(out), out, 0.0)  # empty bins -> 0
            return jnp.moveaxis(out, -1, 0)  # [C, oh, ow]

        return jax.vmap(one_roi)(bx, img_of_roi).astype(xd.dtype)

    return apply(fn, x, _name="roi_pool")


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference box_coder_kernel).

    encode: targets [N,4] x priors [M,4] -> [N, M, 4] (every target
    against every prior). decode: target_box [N, M, 4] deltas; priors
    broadcast along dim `axis` (0: priors indexed by M, 1: by N), output
    [N, M, 4]."""
    pb = _data(prior_box).astype(jnp.float32)
    tb = _data(target_box).astype(jnp.float32)
    pv = (_data(prior_box_var).astype(jnp.float32)
          if prior_box_var is not None else jnp.ones_like(pb))
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        # [N, 1] targets x [1, M] priors -> [N, M]
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :] / pv[None, :, 0],
            (tcy[:, None] - pcy[None, :]) / ph[None, :] / pv[None, :, 1],
            jnp.log(tw[:, None] / pw[None, :]) / pv[None, :, 2],
            jnp.log(th[:, None] / ph[None, :]) / pv[None, :, 3],
        ], axis=-1)
    else:  # decode_center_size: tb is [N, M, 4] deltas
        if tb.ndim == 2:
            tb = tb[:, None, :]
        # broadcast priors along `axis`: 0 -> index by M (dim 1),
        # 1 -> index by N (dim 0)
        expand = (lambda a: a[None, :]) if axis == 0 else (lambda a: a[:, None])
        pvx = (lambda a: a[None, :]) if axis == 0 else (lambda a: a[:, None])
        dcx = pvx(pv[:, 0]) * tb[..., 0] * expand(pw) + expand(pcx)
        dcy = pvx(pv[:, 1]) * tb[..., 1] * expand(ph) + expand(pcy)
        dw = jnp.exp(pvx(pv[:, 2]) * tb[..., 2]) * expand(pw)
        dh = jnp.exp(pvx(pv[:, 3]) * tb[..., 3]) * expand(ph)
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2 - norm, dcy + dh / 2 - norm], axis=-1)
    return Tensor(out)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (reference prior_box_kernel): -> (boxes [H,W,P,4],
    variances [H,W,P,4]) normalized to [0,1]."""
    fh, fw = _data(input).shape[2:]
    ih, iw = _data(image).shape[2:]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    sizes = []
    for ms in min_sizes:
        mx = max_sizes[min_sizes.index(ms)] if max_sizes else None
        if min_max_aspect_ratios_order:
            # Caffe layout: [min box, max box, other-ar boxes] — must match
            # the conv head's channel order (reference prior_box_kernel's
            # min_max_aspect_ratios_order branch)
            sizes.append((float(ms), float(ms)))
            if mx is not None:
                sizes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for a in ars:
                if abs(a - 1.0) < 1e-6:
                    continue
                sizes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
        else:
            for a in ars:
                sizes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
            if mx is not None:
                sizes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    sizes = np.asarray(sizes, np.float32)  # [P, 2] (w, h)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    cxg, cyg = np.meshgrid(cx, cy)
    boxes = np.stack([
        (cxg[..., None] - sizes[None, None, :, 0] / 2) / iw,
        (cyg[..., None] - sizes[None, None, :, 1] / 2) / ih,
        (cxg[..., None] + sizes[None, None, :, 0] / 2) / iw,
        (cyg[..., None] + sizes[None, None, :, 1] / 2) / ih,
    ], axis=-1)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), boxes.shape)
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var.copy()))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """Assign rois to FPN levels by scale (reference
    distribute_fpn_proposals_kernel)."""
    rois = _data(fpn_rois)
    scale = jnp.sqrt((rois[:, 2] - rois[:, 0]) * (rois[:, 3] - rois[:, 1]))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    lvl_np = np.asarray(jax.device_get(lvl))
    rois_np = np.asarray(jax.device_get(rois))
    outs, idxs = [], []
    per_level_counts = []
    rn = (np.asarray(jax.device_get(_data(rois_num)))
          if rois_num is not None else None)
    img_of = (np.repeat(np.arange(len(rn)), rn) if rn is not None else None)
    for level in range(min_level, max_level + 1):
        sel = np.nonzero(lvl_np == level)[0]
        outs.append(Tensor(jnp.asarray(rois_np[sel])))
        idxs.append(sel)
        if rn is not None:
            # per-image roi counts at this level (reference's third output)
            per_level_counts.append(Tensor(jnp.asarray(np.bincount(
                img_of[sel], minlength=len(rn)).astype(np.int32))))
    restore = np.argsort(np.concatenate(idxs)) if idxs else np.zeros(0)
    restore_t = Tensor(jnp.asarray(restore.astype(np.int32)))
    if rn is not None:
        return outs, restore_t, per_level_counts
    return outs, restore_t
