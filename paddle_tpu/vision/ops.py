"""paddle.vision.ops (reference: `python/paddle/vision/ops.py` — detection
primitives backed by `paddle/phi/kernels/*/nms_kernel.*`,
`roi_align_kernel.*`, `box_coder_kernel.*`, `prior_box_kernel.*`).

TPU-native notes: roi_align is a batched bilinear gather (vectorizes
cleanly); nms is an O(n^2) suppression matrix + lax.fori greedy sweep —
static shapes, no host round trip, fine at detection-head sizes (n <= a few
thousand); box_coder/prior_box are pure elementwise math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor, apply

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "prior_box",
           "box_area", "box_iou", "distribute_fpn_proposals",
           "box_clip", "bipartite_match", "collect_fpn_proposals"]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def box_area(boxes):
    b = _data(boxes)
    return Tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def _iou_matrix(a, b):
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter, 1e-10)


def box_iou(boxes1, boxes2):
    return Tensor(_iou_matrix(_data(boxes1), _data(boxes2)))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS -> kept indices sorted by score (reference ops.yaml nms /
    vision/ops.py:nms). Category-aware when category_idxs is given (boxes
    of different categories never suppress each other)."""
    b = _data(boxes)
    n = b.shape[0]
    s = (_data(scores) if scores is not None
         else jnp.arange(n, 0, -1, dtype=jnp.float32))
    order = jnp.argsort(-s)
    b_sorted = b[order]
    iou = _iou_matrix(b_sorted, b_sorted)
    if category_idxs is not None:
        c = _data(category_idxs)[order]
        same = c[:, None] == c[None, :]
        iou = jnp.where(same, iou, 0.0)

    idx = jnp.arange(n)

    def body(i, keep):
        # box i (in score order) survives unless a higher-scored SURVIVOR
        # overlaps it beyond the threshold
        sup = jnp.any((idx < i) & (iou[i] > iou_threshold) & keep)
        return keep.at[i].set(~sup)

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # kept indices have data-dependent count: finalize on host (the
    # reference kernel also returns a dynamic-size index tensor)
    keep_np = np.asarray(jax.device_get(keep))
    order_np = np.asarray(jax.device_get(order))
    out = order_np[keep_np]
    if top_k is not None:
        out = out[:top_k]
    return Tensor(jnp.asarray(out.astype(np.int64)))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference roi_align_kernel): x [N,C,H,W]; boxes [R,4]
    (x1,y1,x2,y2 in input coords); boxes_num [N] rois per image ->
    [R, C, oh, ow]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    bx = _data(boxes).astype(jnp.float32)
    bn = np.asarray(jax.device_get(_data(boxes_num)))
    img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    offset = 0.5 if aligned else 0.0

    # sampling_ratio <= 0: per-RoI adaptive count ceil(roi_h / pooled_h)
    # like the reference roi_align_kernel; the grid buffer is statically
    # sized to the LARGEST RoI's count (capped at 8 per bin dim so one
    # whole-image box cannot inflate every RoI's grid to OOM scale — beyond
    # ~8 samples/bin the bin mean has converged) and smaller RoIs mask the
    # tail slots.
    _ADAPTIVE_CAP = 8
    if sampling_ratio > 0:
        Ry = Rx = int(sampling_ratio)
    else:
        bhost = np.asarray(jax.device_get(bx), np.float32)
        rh_all = np.maximum((bhost[:, 3] - bhost[:, 1]) * spatial_scale, 1e-3)
        rw_all = np.maximum((bhost[:, 2] - bhost[:, 0]) * spatial_scale, 1e-3)
        Ry = max(1, int(np.ceil(rh_all.max() / oh))) if len(bhost) else 1
        Rx = max(1, int(np.ceil(rw_all.max() / ow))) if len(bhost) else 1
        Ry, Rx = min(Ry, _ADAPTIVE_CAP), min(Rx, _ADAPTIVE_CAP)

    def fn(xd):
        n, c, h, w = xd.shape

        def one_roi(roi, img):
            x1, y1, x2, y2 = roi * spatial_scale - offset
            rw = jnp.maximum(x2 - x1, 1e-3)
            rh = jnp.maximum(y2 - y1, 1e-3)
            bin_w, bin_h = rw / ow, rh / oh
            if sampling_ratio > 0:
                ry = jnp.asarray(Ry, jnp.float32)
                rx = jnp.asarray(Rx, jnp.float32)
            else:
                ry = jnp.clip(jnp.ceil(rh / oh), 1, Ry)
                rx = jnp.clip(jnp.ceil(rw / ow), 1, Rx)
            ky = jnp.arange(Ry, dtype=jnp.float32)
            kx = jnp.arange(Rx, dtype=jnp.float32)
            my = (ky < ry).astype(jnp.float32)  # active sample slots
            mx = (kx < rx).astype(jnp.float32)
            gy = (y1 + (jnp.arange(oh)[:, None] + (ky[None, :] + 0.5) / ry)
                  * bin_h)
            gx = (x1 + (jnp.arange(ow)[:, None] + (kx[None, :] + 0.5) / rx)
                  * bin_w)
            gy = gy.reshape(-1)  # [oh*Ry]
            gx = gx.reshape(-1)  # [ow*Rx]
            img_feat = xd[img]  # [C, H, W]

            def bilinear(yy, xx):
                # reference zeroes samples with y < -1 or y > H (outside the
                # feature map beyond the half-pixel border) instead of
                # border-clamping them
                vy = ((yy >= -1) & (yy <= h)).astype(jnp.float32)
                vx = ((xx >= -1) & (xx <= w)).astype(jnp.float32)
                y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
                x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
                y1_ = jnp.clip(y0 + 1, 0, h - 1)
                x1_ = jnp.clip(x0 + 1, 0, w - 1)
                wy = jnp.clip(yy - y0, 0, 1)
                wx = jnp.clip(xx - x0, 0, 1)
                y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
                y1i, x1i = y1_.astype(jnp.int32), x1_.astype(jnp.int32)
                v = (img_feat[:, y0i[:, None], x0i[None, :]] * ((1 - wy)[:, None] * (1 - wx)[None, :])
                     + img_feat[:, y0i[:, None], x1i[None, :]] * ((1 - wy)[:, None] * wx[None, :])
                     + img_feat[:, y1i[:, None], x0i[None, :]] * (wy[:, None] * (1 - wx)[None, :])
                     + img_feat[:, y1i[:, None], x1i[None, :]] * (wy[:, None] * wx[None, :]))
                return v * (vy[:, None] * vx[None, :])  # [C, len(yy), len(xx)]

            vals = bilinear(gy, gx)  # [C, oh*Ry, ow*Rx]
            vals = vals.reshape(c, oh, Ry, ow, Rx)
            vals = vals * my[None, None, :, None, None] \
                * mx[None, None, None, None, :]
            return vals.sum(axis=(2, 4)) / (ry * rx)

        return jax.vmap(one_roi)(bx, img_of_roi).astype(xd.dtype)

    return apply(fn, x, _name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool RoI (reference roi_pool_kernel): TRUE max over every pixel
    whose coordinates fall in a bin (sparse sampling can miss the max), via
    per-bin masks reduced over H,W — XLA fuses the where+max so the
    [oh,ow,H,W] mask never materializes against the channel dim."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bx = _data(boxes).astype(jnp.float32)
    bn = np.asarray(jax.device_get(_data(boxes_num)))
    img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    def fn(xd):
        n, c, h, w = xd.shape
        ygrid = jnp.arange(h, dtype=jnp.float32)
        xgrid = jnp.arange(w, dtype=jnp.float32)

        def one_roi(roi, img):
            x1, y1, x2, y2 = jnp.round(roi * spatial_scale)
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            # bin boundaries (reference: floor/ceil of fractional edges)
            ys0 = y1 + jnp.floor(jnp.arange(oh) * rh / oh)
            ys1 = y1 + jnp.ceil((jnp.arange(oh) + 1) * rh / oh)
            xs0 = x1 + jnp.floor(jnp.arange(ow) * rw / ow)
            xs1 = x1 + jnp.ceil((jnp.arange(ow) + 1) * rw / ow)
            my = ((ygrid[None, :] >= ys0[:, None])
                  & (ygrid[None, :] < ys1[:, None]))   # [oh, H]
            mx = ((xgrid[None, :] >= xs0[:, None])
                  & (xgrid[None, :] < xs1[:, None]))   # [ow, W]
            mask = my[:, None, :, None] & mx[None, :, None, :]  # [oh,ow,H,W]
            feat = xd[img][None, None].astype(jnp.float32)  # [1,1,C,H,W]
            vals = jnp.where(mask[:, :, None], feat, -jnp.inf)
            out = vals.max(axis=(-2, -1))  # [oh, ow, C]
            out = jnp.where(jnp.isfinite(out), out, 0.0)  # empty bins -> 0
            return jnp.moveaxis(out, -1, 0)  # [C, oh, ow]

        return jax.vmap(one_roi)(bx, img_of_roi).astype(xd.dtype)

    return apply(fn, x, _name="roi_pool")


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference box_coder_kernel).

    encode: targets [N,4] x priors [M,4] -> [N, M, 4] (every target
    against every prior). decode: target_box [N, M, 4] deltas; priors
    broadcast along dim `axis` (0: priors indexed by M, 1: by N), output
    [N, M, 4]."""
    pb = _data(prior_box).astype(jnp.float32)
    tb = _data(target_box).astype(jnp.float32)
    pv = (_data(prior_box_var).astype(jnp.float32)
          if prior_box_var is not None else jnp.ones_like(pb))
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        # [N, 1] targets x [1, M] priors -> [N, M]
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :] / pv[None, :, 0],
            (tcy[:, None] - pcy[None, :]) / ph[None, :] / pv[None, :, 1],
            jnp.log(tw[:, None] / pw[None, :]) / pv[None, :, 2],
            jnp.log(th[:, None] / ph[None, :]) / pv[None, :, 3],
        ], axis=-1)
    else:  # decode_center_size: tb is [N, M, 4] deltas
        if tb.ndim == 2:
            tb = tb[:, None, :]
        # broadcast priors along `axis`: 0 -> index by M (dim 1),
        # 1 -> index by N (dim 0)
        expand = (lambda a: a[None, :]) if axis == 0 else (lambda a: a[:, None])
        pvx = (lambda a: a[None, :]) if axis == 0 else (lambda a: a[:, None])
        dcx = pvx(pv[:, 0]) * tb[..., 0] * expand(pw) + expand(pcx)
        dcy = pvx(pv[:, 1]) * tb[..., 1] * expand(ph) + expand(pcy)
        dw = jnp.exp(pvx(pv[:, 2]) * tb[..., 2]) * expand(pw)
        dh = jnp.exp(pvx(pv[:, 3]) * tb[..., 3]) * expand(ph)
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2 - norm, dcy + dh / 2 - norm], axis=-1)
    return Tensor(out)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (reference prior_box_kernel): -> (boxes [H,W,P,4],
    variances [H,W,P,4]) normalized to [0,1]."""
    fh, fw = _data(input).shape[2:]
    ih, iw = _data(image).shape[2:]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    sizes = []
    for ms in min_sizes:
        mx = max_sizes[min_sizes.index(ms)] if max_sizes else None
        if min_max_aspect_ratios_order:
            # Caffe layout: [min box, max box, other-ar boxes] — must match
            # the conv head's channel order (reference prior_box_kernel's
            # min_max_aspect_ratios_order branch)
            sizes.append((float(ms), float(ms)))
            if mx is not None:
                sizes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for a in ars:
                if abs(a - 1.0) < 1e-6:
                    continue
                sizes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
        else:
            for a in ars:
                sizes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
            if mx is not None:
                sizes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    sizes = np.asarray(sizes, np.float32)  # [P, 2] (w, h)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    cxg, cyg = np.meshgrid(cx, cy)
    boxes = np.stack([
        (cxg[..., None] - sizes[None, None, :, 0] / 2) / iw,
        (cyg[..., None] - sizes[None, None, :, 1] / 2) / ih,
        (cxg[..., None] + sizes[None, None, :, 0] / 2) / iw,
        (cyg[..., None] + sizes[None, None, :, 1] / 2) / ih,
    ], axis=-1)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), boxes.shape)
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var.copy()))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """Assign rois to FPN levels by scale (reference
    distribute_fpn_proposals_kernel)."""
    rois = _data(fpn_rois)
    scale = jnp.sqrt((rois[:, 2] - rois[:, 0]) * (rois[:, 3] - rois[:, 1]))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    lvl_np = np.asarray(jax.device_get(lvl))
    rois_np = np.asarray(jax.device_get(rois))
    outs, idxs = [], []
    per_level_counts = []
    rn = (np.asarray(jax.device_get(_data(rois_num)))
          if rois_num is not None else None)
    img_of = (np.repeat(np.arange(len(rn)), rn) if rn is not None else None)
    for level in range(min_level, max_level + 1):
        sel = np.nonzero(lvl_np == level)[0]
        outs.append(Tensor(jnp.asarray(rois_np[sel])))
        idxs.append(sel)
        if rn is not None:
            # per-image roi counts at this level (reference's third output)
            per_level_counts.append(Tensor(jnp.asarray(np.bincount(
                img_of[sel], minlength=len(rn)).astype(np.int32))))
    restore = np.argsort(np.concatenate(idxs)) if idxs else np.zeros(0)
    restore_t = Tensor(jnp.asarray(restore.astype(np.int32)))
    if rn is not None:
        return outs, restore_t, per_level_counts
    return outs, restore_t


def box_clip(input, im_info, name=None):
    """Clip boxes to the image boundary (reference
    `paddle/phi/ops/yaml/ops.yaml:715` box_clip,
    `phi/kernels/cpu/box_clip_kernel.cc`): im_info rows are
    (height, width, scale); boxes live in the UN-scaled input image, so
    the limits are (dim / scale) - 1. Pure elementwise min/max —
    differentiable (clip's subgradient), vectorizes trivially."""
    def fn(b, info):
        info = info.astype(jnp.float32)
        if b.ndim != 3:
            info = info.reshape(-1)[:3]
            lim_h = info[0] / info[2] - 1.0
            lim_w = info[1] / info[2] - 1.0
        else:
            lim_h = (info[:, 0] / info[:, 2] - 1.0)[:, None, None]
            lim_w = (info[:, 1] / info[:, 2] - 1.0)[:, None, None]
        x1, y1, x2, y2 = (b[..., 0:1], b[..., 1:2], b[..., 2:3],
                          b[..., 3:4])
        zero = jnp.zeros((), b.dtype)

        def cl(v, lim):
            return jnp.maximum(jnp.minimum(v, lim.astype(b.dtype)), zero)

        return jnp.concatenate(
            [cl(x1, lim_w), cl(y1, lim_h), cl(x2, lim_w), cl(y2, lim_h)],
            axis=-1)

    return apply(fn, input, im_info, _name="box_clip")


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    """Greedy bipartite matching on a similarity matrix (reference
    `ops.yaml:620` bipartite_match, `phi/kernels/cpu/bipartite_match_kernel.cc`
    — the SSD/MultiBox target-assignment op).

    dist_matrix: [n, m] (or [B, n, m]) similarities, rows = candidates
    (e.g. ground-truth), cols = predictions (e.g. priors). Returns
    (col_to_row_match_indices, col_to_row_match_dist), each [B?, m]:
    column j's matched row (or -1) and its similarity.

    TPU-native: min(n, m) iterations of a global argmax with matched
    rows/cols masked out — a lax.fori_loop over a static bound, no
    host round trips. match_type='per_prediction' additionally matches
    every still-unmatched column to its argmax row when the similarity
    reaches dist_threshold."""
    if match_type not in ("bipartite", "per_prediction"):
        raise ValueError("match_type must be 'bipartite' or "
                         "'per_prediction'")
    d = _data(dist_matrix).astype(jnp.float32)
    batched = d.ndim == 3
    if not batched:
        d = d[None]

    B, n, m = d.shape
    NEG = jnp.float32(-1e30)

    def one(mat):
        def body(_, carry):
            work, idx, dist = carry
            flat = jnp.argmax(work)
            i, j = flat // m, flat % m
            best = work[i, j]
            ok = best > NEG / 2  # anything left to match?
            idx = jnp.where(ok, idx.at[j].set(i), idx)
            dist = jnp.where(ok, dist.at[j].set(best), dist)
            work = jnp.where(ok, work.at[i, :].set(NEG), work)
            work = jnp.where(ok, work.at[:, j].set(NEG), work)
            return work, idx, dist

        idx0 = jnp.full((m,), -1, jnp.int32)
        dist0 = jnp.zeros((m,), jnp.float32)
        work, idx, dist = jax.lax.fori_loop(
            0, min(n, m), body, (mat, idx0, dist0))
        if match_type == "per_prediction":
            cand = jnp.argmax(mat, axis=0)
            cand_d = jnp.max(mat, axis=0)
            take = (idx < 0) & (cand_d >= dist_threshold)
            idx = jnp.where(take, cand.astype(jnp.int32), idx)
            dist = jnp.where(take, cand_d, dist)
        return idx, dist

    idx, dist = jax.vmap(one)(d)
    if not batched:
        idx, dist = idx[0], dist[0]
    return Tensor(idx), Tensor(dist)


def collect_fpn_proposals(multi_rois, multi_scores, min_level=None,
                          max_level=None, post_nms_top_n=-1,
                          rois_num_per_level=None, name=None):
    """Collect proposals across FPN levels and keep the post_nms_top_n
    highest-scoring (reference `ops.yaml:971` collect_fpn_proposals,
    `phi/kernels/.../collect_fpn_proposals_kernel`): concat + one top_k —
    static shapes, single fused XLA program."""
    rois = jnp.concatenate([_data(r) for r in multi_rois], axis=0)
    scores = jnp.concatenate(
        [_data(s).reshape(-1) for s in multi_scores], axis=0)
    if rois_num_per_level is None:
        # single-image form: one global top-k on device
        k = scores.shape[0] if post_nms_top_n in (-1, None) \
            else min(int(post_nms_top_n), scores.shape[0])
        top, sel = jax.lax.top_k(scores, k)
        out = jnp.take(rois, sel, axis=0)
        return Tensor(out), Tensor(jnp.asarray([k], jnp.int32))
    # batched form: rois_num_per_level[l] is a [B] split of level l —
    # collect PER IMAGE (the reference's multi_level_rois_num path) so a
    # batch's proposals never mix; ragged packing is host-side
    per_level = [np.asarray(_data(n)).ravel() for n in rois_num_per_level]
    B = len(per_level[0])
    rois_h = np.asarray(rois, np.float32)
    sc_h = np.asarray(scores, np.float32)
    level_off = np.cumsum([0] + [int(p.sum()) for p in per_level])
    outs, counts = [], []
    for bi in range(B):
        idxs = []
        for li, p in enumerate(per_level):
            s = level_off[li] + int(p[:bi].sum())
            idxs.extend(range(s, s + int(p[bi])))
        idxs = np.asarray(idxs, np.int64)
        order = idxs[np.argsort(-sc_h[idxs])]
        if post_nms_top_n not in (-1, None):
            order = order[:int(post_nms_top_n)]
        outs.append(rois_h[order])
        counts.append(len(order))
    out = (np.concatenate(outs, axis=0) if outs
           else np.zeros((0, 4), np.float32))
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray(np.asarray(counts, np.int32))))


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode a YOLOv3 head into boxes + class scores (reference yolo_box,
    `phi/kernels/.../yolo_box_kernel`): x [B, A*(5+C), H, W] with A =
    len(anchors)//2. Returns (boxes [B, H*W*A, 4] in xyxy image coords,
    scores [B, H*W*A, C]). Pure elementwise grid math — one fused XLA
    program, no host round trip. Detections under conf_thresh get zeroed
    scores (the dense-shape analogue of the reference's filtering)."""
    xd = _data(x).astype(jnp.float32)
    im = _data(img_size).astype(jnp.float32)
    B, _, H, W = xd.shape
    A = len(anchors) // 2
    C = int(class_num)
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
    if iou_aware:
        # reference layout (yolo_box_util.h GetIoUIndex): the A iou
        # channels come FIRST, then the A*(5+C) conv channels
        iou_pred = jax.nn.sigmoid(xd[:, :A])
        feat = xd[:, A:].reshape(B, A, 5 + C, H, W)
    else:
        feat = xd.reshape(B, A, 5 + C, H, W)
    tx, ty, tw, th, tobj = (feat[:, :, 0], feat[:, :, 1], feat[:, :, 2],
                            feat[:, :, 3], feat[:, :, 4])
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    bx = (jax.nn.sigmoid(tx) * scale_x_y
          - (scale_x_y - 1) / 2 + gx) / W
    by = (jax.nn.sigmoid(ty) * scale_x_y
          - (scale_x_y - 1) / 2 + gy) / H
    input_w = W * downsample_ratio
    input_h = H * downsample_ratio
    bw = jnp.exp(tw) * an[None, :, None, None, 0] / input_w
    bh = jnp.exp(th) * an[None, :, None, None, 1] / input_h
    imh = im[:, 0][:, None, None, None]
    imw = im[:, 1][:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    obj = jax.nn.sigmoid(tobj)
    if iou_aware:
        obj = obj ** (1 - iou_aware_factor) * iou_pred ** iou_aware_factor
    cls = jax.nn.sigmoid(feat[:, :, 5:5 + C])
    scores = obj[:, :, None] * cls
    conf_mask = (obj >= conf_thresh)[:, :, None]
    scores = jnp.where(conf_mask, scores, 0.0)

    def flat(v):  # [B, A, H, W] -> [B, A*H*W]
        return v.reshape(B, A * H * W)

    boxes = jnp.stack([flat(x1), flat(y1), flat(x2), flat(y2)], axis=-1)
    sc = scores.transpose(0, 1, 3, 4, 2).reshape(B, A * H * W, C)
    return Tensor(boxes), Tensor(sc)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference matrix_nms op; SOLOv2's parallel soft-NMS):
    instead of the greedy sweep, every detection's score is decayed by its
    IoU with all higher-scored detections of the same class:
    decay = min_j f(iou_ij) / f(max_k iou_jk). Host-side output packing
    (the result count is data-dependent), matmul-style IoU matrix math."""
    b = np.asarray(_data(bboxes), np.float32)
    s = np.asarray(_data(scores), np.float32)
    B, C, N = s.shape
    outs, indices, counts = [], [], []
    for bi in range(B):
        dets = []
        for c in range(C):
            if c == background_label:
                continue
            sc = s[bi, c]
            keep = np.where(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[keep])][:nms_top_k]
            bb = b[bi, order]
            ss = sc[order]
            x1, y1, x2, y2 = bb[:, 0], bb[:, 1], bb[:, 2], bb[:, 3]
            off = 0.0 if normalized else 1.0
            area = (x2 - x1 + off) * (y2 - y1 + off)
            ix1 = np.maximum(x1[:, None], x1[None, :])
            iy1 = np.maximum(y1[:, None], y1[None, :])
            ix2 = np.minimum(x2[:, None], x2[None, :])
            iy2 = np.minimum(y2[:, None], y2[None, :])
            iw = np.maximum(ix2 - ix1 + off, 0)
            ih = np.maximum(iy2 - iy1 + off, 0)
            iou = iw * ih / np.maximum(
                area[:, None] + area[None, :] - iw * ih, 1e-10)
            iou = np.triu(iou, k=1)  # iou[i, j]: higher-scored i vs j
            comp = iou.max(axis=0)   # det i's own max overlap upstream
            if use_gaussian:
                decay = np.exp(-(iou ** 2 - comp[:, None] ** 2)
                               / gaussian_sigma)
            else:
                decay = (1 - iou) / np.maximum(1 - comp[:, None], 1e-10)
            decay = np.where(np.triu(np.ones_like(iou), k=1) > 0,
                             decay, 1.0).min(axis=0)
            new_s = ss * decay
            ok = np.where(new_s >= post_threshold)[0]
            for j in ok:
                dets.append((c, new_s[j], *bb[j], bi * C * N + c * N
                             + order[j]))
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        counts.append(len(dets))
        for d in dets:
            outs.append(d[:6])
            indices.append(d[6])
    out = (np.asarray(outs, np.float32).reshape(-1, 6) if outs
           else np.zeros((0, 6), np.float32))
    res = [Tensor(jnp.asarray(out))]
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(indices, np.int64))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(counts, np.int32))))
    return tuple(res) if len(res) > 1 else res[0]


def multiclass_nms3(bboxes, scores, rois_num=None, score_threshold=0.05,
                    nms_top_k=1000, keep_top_k=100, nms_threshold=0.3,
                    normalized=True, nms_eta=1.0, background_label=0,
                    return_index=False, name=None):
    """Per-class greedy NMS + cross-class top-k (reference multiclass_nms3,
    `phi/kernels/.../multiclass_nms3_kernel`): bboxes [B, N, 4], scores
    [B, C, N]. Returns (out [M, 6] rows (label, score, x1, y1, x2, y2),
    [index], rois_num [B]). Host-side packing like the reference CPU
    kernel; the per-class suppression reuses the device nms."""
    b = np.asarray(_data(bboxes), np.float32)
    s = np.asarray(_data(scores), np.float32)
    B, C, N = s.shape
    outs, idxs, counts = [], [], []
    for bi in range(B):
        dets = []
        for c in range(C):
            if c == background_label:
                continue
            sc = s[bi, c]
            cand = np.where(sc > score_threshold)[0]
            if cand.size == 0:
                continue
            cand = cand[np.argsort(-sc[cand])][:nms_top_k]
            kept = np.asarray(nms(Tensor(jnp.asarray(b[bi, cand])),
                                  iou_threshold=nms_threshold).numpy())
            for j in kept:
                gi = cand[int(j)]
                dets.append((c, sc[gi], *b[bi, gi], bi * N + gi))
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        counts.append(len(dets))
        for d in dets:
            outs.append(d[:6])
            idxs.append(d[6])
    out = (np.asarray(outs, np.float32).reshape(-1, 6) if outs
           else np.zeros((0, 6), np.float32))
    res = [Tensor(jnp.asarray(out))]
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(idxs, np.int64))))
    res.append(Tensor(jnp.asarray(np.asarray(counts, np.int32))))
    return tuple(res)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True, name=None):
    """RPN proposal generation (reference generate_proposals_v2,
    `phi/kernels/.../generate_proposals_kernel`): per image — decode
    anchor deltas (box_coder math), clip to the image, drop tiny boxes,
    top pre_nms_top_n by score, greedy NMS, top post_nms_top_n. Decode +
    clip run on device; the ragged packing is host-side."""
    sc = np.asarray(_data(scores), np.float32)       # [B, A, H, W]
    bd = np.asarray(_data(bbox_deltas), np.float32)  # [B, A*4, H, W]
    ims = np.asarray(_data(img_size), np.float32)    # [B, 2] (h, w)
    an = np.asarray(_data(anchors), np.float32).reshape(-1, 4)
    var = np.asarray(_data(variances), np.float32).reshape(-1, 4)
    B, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0
    all_rois, counts = [], []
    for bi in range(B):
        score = sc[bi].transpose(1, 2, 0).reshape(-1)       # H*W*A
        delta = bd[bi].reshape(A, 4, H, W).transpose(
            2, 3, 0, 1).reshape(-1, 4)
        aw = an[:, 2] - an[:, 0] + off
        ah = an[:, 3] - an[:, 1] + off
        acx = an[:, 0] + aw / 2
        acy = an[:, 1] + ah / 2
        cx = var[:, 0] * delta[:, 0] * aw + acx
        cy = var[:, 1] * delta[:, 1] * ah + acy
        w = np.exp(np.minimum(var[:, 2] * delta[:, 2], 10.0)) * aw
        h = np.exp(np.minimum(var[:, 3] * delta[:, 3], 10.0)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], axis=1)
        imh, imw = ims[bi]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, imw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, imh - off)
        ws = boxes[:, 2] - boxes[:, 0] + off
        hs = boxes[:, 3] - boxes[:, 1] + off
        valid = np.where((ws >= min_size) & (hs >= min_size))[0]
        order = valid[np.argsort(-score[valid])][:pre_nms_top_n]
        if order.size == 0:
            counts.append(0)
            continue
        kept = np.asarray(nms(Tensor(jnp.asarray(boxes[order])),
                              iou_threshold=nms_thresh).numpy())
        kept = order[kept[:post_nms_top_n]]
        all_rois.append(boxes[kept])
        counts.append(len(kept))
    rois = (np.concatenate(all_rois, axis=0) if all_rois
            else np.zeros((0, 4), np.float32))
    out = (Tensor(jnp.asarray(rois)),)
    if return_rois_num:
        out = out + (Tensor(jnp.asarray(np.asarray(counts, np.int32))),)
    return out


def psroi_pool(x, boxes, boxes_num, output_size=None, spatial_scale=1.0,
               pooled_height=1, pooled_width=1, output_channels=None,
               name=None):
    """Position-sensitive ROI pooling (reference psroi_pool,
    `phi/kernels/.../psroi_pool_kernel`; R-FCN): x [B, C, H, W] with
    C = out_c * ph * pw — output channel (i, j) bin pools its OWN channel
    group. Implemented as bin-center bilinear sampling + average (the
    PSROIAlign formulation — continuous sampling instead of the
    reference's integer binning, same capability, TPU-friendly gathers)."""
    xd = _data(x).astype(jnp.float32)
    bx = _data(boxes).astype(jnp.float32)
    bn = np.asarray(_data(boxes_num)).ravel()
    if output_size is None:
        ph, pw = int(pooled_height), int(pooled_width)
    else:
        ph, pw = ((output_size, output_size)
                  if isinstance(output_size, int) else output_size)
    B, C, H, W = xd.shape
    out_c = C // (ph * pw)
    batch_of = np.repeat(np.arange(len(bn)), bn)

    def one(box, bidx):
        x1, y1, x2, y2 = box * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1) / pw
        rh = jnp.maximum(y2 - y1, 0.1) / ph
        js, is_ = jnp.meshgrid(jnp.arange(pw, dtype=jnp.float32),
                               jnp.arange(ph, dtype=jnp.float32))
        cx = x1 + (js + 0.5) * rw   # [ph, pw] bin centers
        cy = y1 + (is_ + 0.5) * rh
        x0 = jnp.clip(jnp.floor(cx), 0, W - 1).astype(jnp.int32)
        y0 = jnp.clip(jnp.floor(cy), 0, H - 1).astype(jnp.int32)
        x1i = jnp.minimum(x0 + 1, W - 1)
        y1i = jnp.minimum(y0 + 1, H - 1)
        fx = jnp.clip(cx, 0, W - 1) - x0
        fy = jnp.clip(cy, 0, H - 1) - y0
        fm = xd[bidx].reshape(out_c, ph, pw, H, W)
        grp = fm[:, jnp.arange(ph)[:, None], jnp.arange(pw)[None, :]]
        # grp: [out_c, ph, pw, H, W]; gather the 4 corners at each bin
        g = lambda yy, xx: grp[:, is_.astype(jnp.int32), js.astype(jnp.int32),
                               yy, xx]  # noqa: E731
        v = (g(y0, x0) * (1 - fx) * (1 - fy) + g(y0, x1i) * fx * (1 - fy)
             + g(y1i, x0) * (1 - fx) * fy + g(y1i, x1i) * fx * fy)
        return v  # [out_c, ph, pw]

    outs = [one(bx[i], int(batch_of[i])) for i in range(bx.shape[0])]
    out = (jnp.stack(outs) if outs
           else jnp.zeros((0, out_c, ph, pw), jnp.float32))
    return Tensor(out)


def correlation(input1, input2, pad_size=4, kernel_size=1,
                max_displacement=4, stride1=1, stride2=1,
                corr_type_multiply=1, name=None):
    """Correlation cost volume (reference correlation op,
    `phi/kernels/gpu/correlation_kernel` — FlowNet's matching layer):
    corr[b, d, y, x] = mean_c x1[b, c, y, x] * x2[b, c, y+dy, x+dx] over
    the (2*max_displacement/stride2 + 1)^2 displacement grid. Implemented
    as shifted elementwise products — D^2 fused multiplies, no gather."""
    if corr_type_multiply != 1:
        raise NotImplementedError(
            "correlation: only corr_type_multiply=1 (multiplicative) is "
            "implemented — the same restriction as the reference kernel")

    def fn(a, b):
        B, C, H, W = a.shape
        pad = [(0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size)]
        ap = jnp.pad(a, pad)
        bp = jnp.pad(b, pad)
        r = max_displacement // stride2
        disps = [(dy * stride2, dx * stride2)
                 for dy in range(-r, r + 1) for dx in range(-r, r + 1)]
        k = int(kernel_size)
        outs = []
        for dy, dx in disps:
            shifted = jnp.roll(bp, shift=(-dy, -dx), axis=(2, 3))
            prod = (ap * shifted).mean(axis=1)  # [B, H+2p, W+2p]
            if k > 1:
                # patch correlation: mean over the kernel_size^2 window
                # centered on each pixel (reference correlation_funcs
                # nelems = K*K*C)
                prod = jax.lax.reduce_window(
                    prod, 0.0, jax.lax.add, (1, k, k), (1, 1, 1),
                    "SAME") / (k * k)
            outs.append(prod[:, pad_size:pad_size + H,
                             pad_size:pad_size + W])
        out = jnp.stack(outs, axis=1)  # [B, D^2, H, W]
        if stride1 > 1:
            out = out[:, :, ::stride1, ::stride1]
        return out

    return apply(fn, input1, input2, _name="correlation")


def yolo_loss(x, gt_box, gt_label, gt_score=None, anchors=(),
              anchor_mask=(), class_num=1, ignore_thresh=0.7,
              downsample_ratio=32, use_label_smooth=True, scale_x_y=1.0,
              name=None):
    """YOLOv3 training loss (reference yolo_loss / yolov3_loss op,
    `phi/kernels/cpu/yolo_loss_kernel.cc`): per ground-truth box, the
    best-wh-IoU anchor (over ALL anchors) owns it; if that anchor is in
    this head's anchor_mask the owning grid cell gets coordinate (BCE xy
    + L2 wh, weighted 2 - w*h), objectness = gt_score (the mixup
    confidence, :342) and label-smoothed class BCE targets
    (smooth_weight = min(1/C, 1/40), :212-217); predictions overlapping
    any gt beyond ignore_thresh are excluded from the noobj objectness
    term. Fully differentiable and VECTORIZED over the gt axis — one
    broadcasted IoU + gather/scatter, graph size independent of G.

    x [B, A*(5+C), H, W]; gt_box [B, G, 4] (cx, cy, w, h normalized);
    gt_label [B, G] int; gt_score [B, G] (None = 1s). Returns loss [B].
    """
    def _bce(p, t):
        p = jnp.clip(jax.nn.sigmoid(p), 1e-7, 1 - 1e-7)
        return -(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))

    if gt_score is None:
        gt_score = Tensor(jnp.ones(_data(gt_label).shape, jnp.float32))

    def _loss(xd, gb, gl, gs):
        xd = xd.astype(jnp.float32)
        gb = gb.astype(jnp.float32)
        gl = gl.astype(jnp.int32)
        gs = gs.astype(jnp.float32)
        B, _, H, W = xd.shape
        A = len(anchor_mask)
        C = int(class_num)
        G = gb.shape[1]
        an_all = jnp.asarray(np.asarray(anchors, np.float32).reshape(-1, 2))
        mask_arr = np.asarray(anchor_mask, np.int64)
        an = an_all[jnp.asarray(mask_arr)]
        input_w = W * downsample_ratio
        input_h = H * downsample_ratio
        feat = xd.reshape(B, A, 5 + C, H, W)
        tx, ty, tw, th, tobj = (feat[:, :, 0], feat[:, :, 1],
                                feat[:, :, 2], feat[:, :, 3], feat[:, :, 4])
        tcls = feat[:, :, 5:]

        # decoded pred boxes (normalized) for the ignore mask
        cgx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        cgy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        px = (jax.nn.sigmoid(tx) + cgx) / W
        py = (jax.nn.sigmoid(ty) + cgy) / H
        pw = jnp.exp(tw) * an[None, :, 0, None, None] / input_w
        ph = jnp.exp(th) * an[None, :, 1, None, None] / input_h

        def iou_xywh(x1, y1, w1, h1, x2, y2, w2, h2):
            l = jnp.maximum(x1 - w1 / 2, x2 - w2 / 2)
            r = jnp.minimum(x1 + w1 / 2, x2 + w2 / 2)
            t = jnp.maximum(y1 - h1 / 2, y2 - h2 / 2)
            bt = jnp.minimum(y1 + h1 / 2, y2 + h2 / 2)
            inter = jnp.clip(r - l, 0) * jnp.clip(bt - t, 0)
            return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-10)

        # ignore mask: best IoU of each prediction vs ALL gts, one
        # broadcast over the G axis ([B, G, A, H, W] transient)
        iou_all = iou_xywh(
            px[:, None], py[:, None], pw[:, None], ph[:, None],
            gb[:, :, 0, None, None, None], gb[:, :, 1, None, None, None],
            gb[:, :, 2, None, None, None], gb[:, :, 3, None, None, None])
        noobj_mask = (iou_all.max(axis=1) < ignore_thresh).astype(
            jnp.float32)

        # per-gt anchor assignment, vectorized over [B, G]
        cx, cy, w, h = gb[..., 0], gb[..., 1], gb[..., 2], gb[..., 3]
        has = (w > 0) & (h > 0)
        gw = w[..., None] * input_w
        gh = h[..., None] * input_h
        iw = jnp.minimum(gw, an_all[None, None, :, 0])
        ih = jnp.minimum(gh, an_all[None, None, :, 1])
        inter = iw * ih
        wh_iou = inter / jnp.maximum(
            gw * gh + an_all[None, None, :, 0] * an_all[None, None, :, 1]
            - inter, 1e-10)
        best_a = jnp.argmax(wh_iou, axis=-1)           # [B, G] global idx
        lut_local = np.full(len(an_all), 0, np.int64)
        lut_in = np.zeros(len(an_all), bool)
        for k, m in enumerate(mask_arr):
            lut_local[m] = k
            lut_in[m] = True
        local_a = jnp.asarray(lut_local)[best_a]       # [B, G]
        own = has & jnp.asarray(lut_in)[best_a]
        m = own.astype(jnp.float32)

        gi = jnp.clip((cx * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((cy * H).astype(jnp.int32), 0, H - 1)
        bidx = jnp.arange(B)[:, None].repeat(G, 1)
        sel = (bidx, local_a, gj, gi)

        t_x = cx * W - gi
        t_y = cy * H - gj
        aw = an[jnp.clip(local_a, 0, A - 1)]           # [B, G, 2]
        t_w = jnp.log(jnp.maximum(w * input_w / aw[..., 0], 1e-9))
        t_h = jnp.log(jnp.maximum(h * input_h / aw[..., 1], 1e-9))
        scale = (2.0 - w * h) * gs

        loss = (m * scale * (_bce(tx[sel], t_x) + _bce(ty[sel], t_y))
                ).sum(-1)
        loss = loss + (m * scale * 0.5 * ((tw[sel] - t_w) ** 2
                                          + (th[sel] - t_h) ** 2)).sum(-1)

        # class targets: smooth_weight = min(1/C, 1/40) (reference
        # :212-217); label_pos = 1 - sw, label_neg = sw
        sw = min(1.0 / C, 1.0 / 40.0) if (use_label_smooth and C > 1)             else 0.0
        cls_t = jax.nn.one_hot(gl, C) * (1.0 - 2.0 * sw) + sw
        cls_pred = tcls[bidx, local_a, :, gj, gi]      # [B, G, C]
        loss = loss + (m * gs * _bce(cls_pred, cls_t).sum(-1)).sum(-1)

        # objectness: the positive target is the MIXUP SCORE (reference
        # :342 obj_mask_data[obj_idx] = score), not 1.0
        obj_target = jnp.zeros((B, A, H, W), jnp.float32)
        obj_target = obj_target.at[sel].max(m * gs)
        pos = (obj_target > 0).astype(jnp.float32)
        loss = loss + (pos * _bce(tobj, obj_target)).sum((1, 2, 3))
        loss = loss + ((1 - pos) * noobj_mask
                       * _bce(tobj, 0.0)).sum((1, 2, 3))
        return loss

    return apply(_loss, x, gt_box, gt_label, gt_score, _name="yolo_loss")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference deformable_conv op,
    `phi/kernels/impl/deformable_conv_kernel_impl.h`; python api
    `vision/ops.py deform_conv2d`): each kernel tap samples the input at
    a LEARNED offset from its integer position (bilinear), optionally
    modulated by `mask` (v2). TPU-native: the deformable im2col is a
    batched bilinear gather per tap (K taps, static loop) followed by one
    einsum — the same gather+MXU pattern as roi_align."""
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    sh, sw = pair(stride)
    ph, pw = pair(padding)
    dh, dw = pair(dilation)

    def fn(xd, off, wd, *rest):
        i = 0
        bd = None
        md = None
        if bias is not None:
            bd = rest[i]
            i += 1
        if mask is not None:
            md = rest[i]
        B, Cin, H, W = xd.shape
        Cout, Cin_g, KH, KW = wd.shape
        K = KH * KW
        Ho = (H + 2 * ph - (dh * (KH - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (KW - 1) + 1)) // sw + 1
        dg = deformable_groups
        off = off.reshape(B, dg, K, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * sh - ph)[None, :, None]
        base_x = (jnp.arange(Wo) * sw - pw)[None, None, :]

        cols = []
        for k in range(K):
            kh, kw = k // KW, k % KW
            # offset layout (reference deformable_conv_functor): (dy, dx)
            py = (base_y + kh * dh) + off[:, :, k, 0]  # [B, dg, Ho, Wo]
            px = (base_x + kw * dw) + off[:, :, k, 1]
            valid = ((py > -1) & (py < H) & (px > -1)
                     & (px < W)).astype(jnp.float32)
            y0 = jnp.floor(py)
            x0 = jnp.floor(px)
            wy = py - y0
            wx = px - x0
            y0i = jnp.clip(y0, 0, H - 1).astype(jnp.int32)
            x0i = jnp.clip(x0, 0, W - 1).astype(jnp.int32)
            y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
            xg = xd.reshape(B, dg, Cin // dg, H, W)

            def gat(yy, xx):
                # flat gather over H*W per (b, dg) -> [B, dg, C/dg, Ho, Wo]
                return jnp.take_along_axis(
                    xg.reshape(B, dg, Cin // dg, H * W),
                    (yy * W + xx)[:, :, None, :, :].reshape(
                        B, dg, 1, Ho * Wo),
                    axis=3).reshape(B, dg, Cin // dg, Ho, Wo)

            v = (gat(y0i, x0i) * ((1 - wy) * (1 - wx))[:, :, None]
                 + gat(y0i, x1i) * ((1 - wy) * wx)[:, :, None]
                 + gat(y1i, x0i) * (wy * (1 - wx))[:, :, None]
                 + gat(y1i, x1i) * (wy * wx)[:, :, None])
            v = v * valid[:, :, None]
            if md is not None:
                mk = md.reshape(B, dg, K, Ho, Wo)[:, :, k]
                v = v * mk[:, :, None]
            cols.append(v.reshape(B, Cin, Ho, Wo))
        col = jnp.stack(cols, axis=2)  # [B, Cin, K, Ho, Wo]
        wg = wd.reshape(groups, Cout // groups, Cin_g, KH * KW)
        cg = col.reshape(B, groups, Cin // groups, K, Ho, Wo)
        out = jnp.einsum("goik,bgikhw->bgohw", wg, cg)
        out = out.reshape(B, Cout, Ho, Wo)
        if bd is not None:
            out = out + bd.reshape(1, -1, 1, 1)
        return out

    args = [x, offset, weight]
    if bias is not None:
        args.append(bias)
    if mask is not None:
        args.append(mask)
    return apply(fn, *args, _name="deform_conv2d")


# yaml op name (ops.yaml deformable_conv); deform_conv2d is the python api
deformable_conv = deform_conv2d


def read_file(filename, dtype="uint8", place=None, name=None):
    """Read raw bytes into a uint8 tensor (reference read_file op,
    `vision/ops.py read_file` — the file half of the decode pipeline)."""
    data = np.fromfile(filename, dtype=np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to [C, H, W] uint8 (reference decode_jpeg
    op, `phi/kernels/gpu/decode_jpeg_kernel` over nvjpeg): host-side via
    PIL here — image decode feeds the input pipeline, not the compiled
    graph."""
    import io

    from PIL import Image

    buf = bytes(np.asarray(_data(x), np.uint8).tobytes())
    img = Image.open(io.BytesIO(buf))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def yolo_box_head(x, anchors=(), class_num=1, name=None):
    """The PP-YOLO inference head transform (reference yolo_box_head op /
    TensorRT plugin `yolo_box_head_op_plugin.cu`): per anchor slot,
    sigmoid on x, y, objectness, and class channels; w/h raw (the decode
    to boxes happens in yolo_box_post). Pure elementwise."""
    def fn(xd):
        B, _, H, W = xd.shape
        A = len(anchors) // 2
        f = xd.reshape(B, A, 5 + class_num, H, W)
        sig = jax.nn.sigmoid(f)
        out = f.at[:, :, 0:2].set(sig[:, :, 0:2])
        out = out.at[:, :, 4:].set(sig[:, :, 4:])
        return out.reshape(B, A * (5 + class_num), H, W)

    return apply(fn, x, _name="yolo_box_head")


def yolo_box_post(boxes0, boxes1, boxes2, image_shape, image_scale,
                  anchors0=(), anchors1=(), anchors2=(), class_num=1,
                  conf_thresh=0.01, downsample_ratio0=32,
                  downsample_ratio1=16, downsample_ratio2=8,
                  clip_bbox=True, scale_x_y=1.0, nms_threshold=0.45,
                  name=None):
    """Multi-level YOLO postprocess (reference yolo_box_post op): decode
    the three heads with yolo_box, concat, threshold, class-aware greedy
    NMS, emit (label, score, x1, y1, x2, y2) rows + per-image counts.
    Device decode + host packing (the output count is data-dependent,
    like the reference kernel)."""
    ims = _data(image_shape).astype(jnp.float32).reshape(-1, 2)
    scale = np.asarray(_data(image_scale), np.float32).reshape(-1)
    levels = ((boxes0, anchors0, downsample_ratio0),
              (boxes1, anchors1, downsample_ratio1),
              (boxes2, anchors2, downsample_ratio2))
    all_boxes, all_scores = [], []
    for feat, an, ds in levels:
        b, s = yolo_box(feat, Tensor(ims), anchors=list(an),
                        class_num=class_num, conf_thresh=conf_thresh,
                        downsample_ratio=ds, clip_bbox=clip_bbox,
                        scale_x_y=scale_x_y)
        all_boxes.append(np.asarray(b.numpy()))
        all_scores.append(np.asarray(s.numpy()))
    bx = np.concatenate(all_boxes, axis=1)    # [B, N, 4]
    sc = np.concatenate(all_scores, axis=1)   # [B, N, C]
    B = bx.shape[0]
    outs, counts = [], []
    for bi in range(B):
        dets = []
        for c in range(class_num):
            s = sc[bi, :, c]
            cand = np.where(s > conf_thresh)[0]
            if cand.size == 0:
                continue
            cand = cand[np.argsort(-s[cand])]
            kept = np.asarray(nms(Tensor(jnp.asarray(bx[bi, cand])),
                                  iou_threshold=nms_threshold).numpy())
            for j in kept:
                gi = cand[int(j)]
                box = bx[bi, gi] / max(scale[bi % len(scale)], 1e-9)
                dets.append((c, s[gi], *box))
        dets.sort(key=lambda d: -d[1])
        counts.append(len(dets))
        outs.extend(d for d in dets)
    out = (np.asarray(outs, np.float32).reshape(-1, 6) if outs
           else np.zeros((0, 6), np.float32))
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray(np.asarray(counts, np.int32))))
