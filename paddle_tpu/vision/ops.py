"""paddle.vision.ops (reference: `python/paddle/vision/ops.py` — detection
primitives backed by `paddle/phi/kernels/*/nms_kernel.*`,
`roi_align_kernel.*`, `box_coder_kernel.*`, `prior_box_kernel.*`).

TPU-native notes: roi_align is a batched bilinear gather (vectorizes
cleanly); nms is an O(n^2) suppression matrix + lax.fori greedy sweep —
static shapes, no host round trip, fine at detection-head sizes (n <= a few
thousand); box_coder/prior_box are pure elementwise math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor, apply

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "prior_box",
           "box_area", "box_iou", "distribute_fpn_proposals",
           "box_clip", "bipartite_match", "collect_fpn_proposals"]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def box_area(boxes):
    b = _data(boxes)
    return Tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def _iou_matrix(a, b):
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None] - inter, 1e-10)


def box_iou(boxes1, boxes2):
    return Tensor(_iou_matrix(_data(boxes1), _data(boxes2)))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS -> kept indices sorted by score (reference ops.yaml nms /
    vision/ops.py:nms). Category-aware when category_idxs is given (boxes
    of different categories never suppress each other)."""
    b = _data(boxes)
    n = b.shape[0]
    s = (_data(scores) if scores is not None
         else jnp.arange(n, 0, -1, dtype=jnp.float32))
    order = jnp.argsort(-s)
    b_sorted = b[order]
    iou = _iou_matrix(b_sorted, b_sorted)
    if category_idxs is not None:
        c = _data(category_idxs)[order]
        same = c[:, None] == c[None, :]
        iou = jnp.where(same, iou, 0.0)

    idx = jnp.arange(n)

    def body(i, keep):
        # box i (in score order) survives unless a higher-scored SURVIVOR
        # overlaps it beyond the threshold
        sup = jnp.any((idx < i) & (iou[i] > iou_threshold) & keep)
        return keep.at[i].set(~sup)

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # kept indices have data-dependent count: finalize on host (the
    # reference kernel also returns a dynamic-size index tensor)
    keep_np = np.asarray(jax.device_get(keep))
    order_np = np.asarray(jax.device_get(order))
    out = order_np[keep_np]
    if top_k is not None:
        out = out[:top_k]
    return Tensor(jnp.asarray(out.astype(np.int64)))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference roi_align_kernel): x [N,C,H,W]; boxes [R,4]
    (x1,y1,x2,y2 in input coords); boxes_num [N] rois per image ->
    [R, C, oh, ow]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    bx = _data(boxes).astype(jnp.float32)
    bn = np.asarray(jax.device_get(_data(boxes_num)))
    img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    offset = 0.5 if aligned else 0.0

    # sampling_ratio <= 0: per-RoI adaptive count ceil(roi_h / pooled_h)
    # like the reference roi_align_kernel; the grid buffer is statically
    # sized to the LARGEST RoI's count (capped at 8 per bin dim so one
    # whole-image box cannot inflate every RoI's grid to OOM scale — beyond
    # ~8 samples/bin the bin mean has converged) and smaller RoIs mask the
    # tail slots.
    _ADAPTIVE_CAP = 8
    if sampling_ratio > 0:
        Ry = Rx = int(sampling_ratio)
    else:
        bhost = np.asarray(jax.device_get(bx), np.float32)
        rh_all = np.maximum((bhost[:, 3] - bhost[:, 1]) * spatial_scale, 1e-3)
        rw_all = np.maximum((bhost[:, 2] - bhost[:, 0]) * spatial_scale, 1e-3)
        Ry = max(1, int(np.ceil(rh_all.max() / oh))) if len(bhost) else 1
        Rx = max(1, int(np.ceil(rw_all.max() / ow))) if len(bhost) else 1
        Ry, Rx = min(Ry, _ADAPTIVE_CAP), min(Rx, _ADAPTIVE_CAP)

    def fn(xd):
        n, c, h, w = xd.shape

        def one_roi(roi, img):
            x1, y1, x2, y2 = roi * spatial_scale - offset
            rw = jnp.maximum(x2 - x1, 1e-3)
            rh = jnp.maximum(y2 - y1, 1e-3)
            bin_w, bin_h = rw / ow, rh / oh
            if sampling_ratio > 0:
                ry = jnp.asarray(Ry, jnp.float32)
                rx = jnp.asarray(Rx, jnp.float32)
            else:
                ry = jnp.clip(jnp.ceil(rh / oh), 1, Ry)
                rx = jnp.clip(jnp.ceil(rw / ow), 1, Rx)
            ky = jnp.arange(Ry, dtype=jnp.float32)
            kx = jnp.arange(Rx, dtype=jnp.float32)
            my = (ky < ry).astype(jnp.float32)  # active sample slots
            mx = (kx < rx).astype(jnp.float32)
            gy = (y1 + (jnp.arange(oh)[:, None] + (ky[None, :] + 0.5) / ry)
                  * bin_h)
            gx = (x1 + (jnp.arange(ow)[:, None] + (kx[None, :] + 0.5) / rx)
                  * bin_w)
            gy = gy.reshape(-1)  # [oh*Ry]
            gx = gx.reshape(-1)  # [ow*Rx]
            img_feat = xd[img]  # [C, H, W]

            def bilinear(yy, xx):
                # reference zeroes samples with y < -1 or y > H (outside the
                # feature map beyond the half-pixel border) instead of
                # border-clamping them
                vy = ((yy >= -1) & (yy <= h)).astype(jnp.float32)
                vx = ((xx >= -1) & (xx <= w)).astype(jnp.float32)
                y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
                x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
                y1_ = jnp.clip(y0 + 1, 0, h - 1)
                x1_ = jnp.clip(x0 + 1, 0, w - 1)
                wy = jnp.clip(yy - y0, 0, 1)
                wx = jnp.clip(xx - x0, 0, 1)
                y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
                y1i, x1i = y1_.astype(jnp.int32), x1_.astype(jnp.int32)
                v = (img_feat[:, y0i[:, None], x0i[None, :]] * ((1 - wy)[:, None] * (1 - wx)[None, :])
                     + img_feat[:, y0i[:, None], x1i[None, :]] * ((1 - wy)[:, None] * wx[None, :])
                     + img_feat[:, y1i[:, None], x0i[None, :]] * (wy[:, None] * (1 - wx)[None, :])
                     + img_feat[:, y1i[:, None], x1i[None, :]] * (wy[:, None] * wx[None, :]))
                return v * (vy[:, None] * vx[None, :])  # [C, len(yy), len(xx)]

            vals = bilinear(gy, gx)  # [C, oh*Ry, ow*Rx]
            vals = vals.reshape(c, oh, Ry, ow, Rx)
            vals = vals * my[None, None, :, None, None] \
                * mx[None, None, None, None, :]
            return vals.sum(axis=(2, 4)) / (ry * rx)

        return jax.vmap(one_roi)(bx, img_of_roi).astype(xd.dtype)

    return apply(fn, x, _name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool RoI (reference roi_pool_kernel): TRUE max over every pixel
    whose coordinates fall in a bin (sparse sampling can miss the max), via
    per-bin masks reduced over H,W — XLA fuses the where+max so the
    [oh,ow,H,W] mask never materializes against the channel dim."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bx = _data(boxes).astype(jnp.float32)
    bn = np.asarray(jax.device_get(_data(boxes_num)))
    img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    def fn(xd):
        n, c, h, w = xd.shape
        ygrid = jnp.arange(h, dtype=jnp.float32)
        xgrid = jnp.arange(w, dtype=jnp.float32)

        def one_roi(roi, img):
            x1, y1, x2, y2 = jnp.round(roi * spatial_scale)
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            # bin boundaries (reference: floor/ceil of fractional edges)
            ys0 = y1 + jnp.floor(jnp.arange(oh) * rh / oh)
            ys1 = y1 + jnp.ceil((jnp.arange(oh) + 1) * rh / oh)
            xs0 = x1 + jnp.floor(jnp.arange(ow) * rw / ow)
            xs1 = x1 + jnp.ceil((jnp.arange(ow) + 1) * rw / ow)
            my = ((ygrid[None, :] >= ys0[:, None])
                  & (ygrid[None, :] < ys1[:, None]))   # [oh, H]
            mx = ((xgrid[None, :] >= xs0[:, None])
                  & (xgrid[None, :] < xs1[:, None]))   # [ow, W]
            mask = my[:, None, :, None] & mx[None, :, None, :]  # [oh,ow,H,W]
            feat = xd[img][None, None].astype(jnp.float32)  # [1,1,C,H,W]
            vals = jnp.where(mask[:, :, None], feat, -jnp.inf)
            out = vals.max(axis=(-2, -1))  # [oh, ow, C]
            out = jnp.where(jnp.isfinite(out), out, 0.0)  # empty bins -> 0
            return jnp.moveaxis(out, -1, 0)  # [C, oh, ow]

        return jax.vmap(one_roi)(bx, img_of_roi).astype(xd.dtype)

    return apply(fn, x, _name="roi_pool")


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference box_coder_kernel).

    encode: targets [N,4] x priors [M,4] -> [N, M, 4] (every target
    against every prior). decode: target_box [N, M, 4] deltas; priors
    broadcast along dim `axis` (0: priors indexed by M, 1: by N), output
    [N, M, 4]."""
    pb = _data(prior_box).astype(jnp.float32)
    tb = _data(target_box).astype(jnp.float32)
    pv = (_data(prior_box_var).astype(jnp.float32)
          if prior_box_var is not None else jnp.ones_like(pb))
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        # [N, 1] targets x [1, M] priors -> [N, M]
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :] / pv[None, :, 0],
            (tcy[:, None] - pcy[None, :]) / ph[None, :] / pv[None, :, 1],
            jnp.log(tw[:, None] / pw[None, :]) / pv[None, :, 2],
            jnp.log(th[:, None] / ph[None, :]) / pv[None, :, 3],
        ], axis=-1)
    else:  # decode_center_size: tb is [N, M, 4] deltas
        if tb.ndim == 2:
            tb = tb[:, None, :]
        # broadcast priors along `axis`: 0 -> index by M (dim 1),
        # 1 -> index by N (dim 0)
        expand = (lambda a: a[None, :]) if axis == 0 else (lambda a: a[:, None])
        pvx = (lambda a: a[None, :]) if axis == 0 else (lambda a: a[:, None])
        dcx = pvx(pv[:, 0]) * tb[..., 0] * expand(pw) + expand(pcx)
        dcy = pvx(pv[:, 1]) * tb[..., 1] * expand(ph) + expand(pcy)
        dw = jnp.exp(pvx(pv[:, 2]) * tb[..., 2]) * expand(pw)
        dh = jnp.exp(pvx(pv[:, 3]) * tb[..., 3]) * expand(ph)
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2 - norm, dcy + dh / 2 - norm], axis=-1)
    return Tensor(out)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (reference prior_box_kernel): -> (boxes [H,W,P,4],
    variances [H,W,P,4]) normalized to [0,1]."""
    fh, fw = _data(input).shape[2:]
    ih, iw = _data(image).shape[2:]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    sizes = []
    for ms in min_sizes:
        mx = max_sizes[min_sizes.index(ms)] if max_sizes else None
        if min_max_aspect_ratios_order:
            # Caffe layout: [min box, max box, other-ar boxes] — must match
            # the conv head's channel order (reference prior_box_kernel's
            # min_max_aspect_ratios_order branch)
            sizes.append((float(ms), float(ms)))
            if mx is not None:
                sizes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for a in ars:
                if abs(a - 1.0) < 1e-6:
                    continue
                sizes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
        else:
            for a in ars:
                sizes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
            if mx is not None:
                sizes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    sizes = np.asarray(sizes, np.float32)  # [P, 2] (w, h)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    cxg, cyg = np.meshgrid(cx, cy)
    boxes = np.stack([
        (cxg[..., None] - sizes[None, None, :, 0] / 2) / iw,
        (cyg[..., None] - sizes[None, None, :, 1] / 2) / ih,
        (cxg[..., None] + sizes[None, None, :, 0] / 2) / iw,
        (cyg[..., None] + sizes[None, None, :, 1] / 2) / ih,
    ], axis=-1)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), boxes.shape)
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var.copy()))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """Assign rois to FPN levels by scale (reference
    distribute_fpn_proposals_kernel)."""
    rois = _data(fpn_rois)
    scale = jnp.sqrt((rois[:, 2] - rois[:, 0]) * (rois[:, 3] - rois[:, 1]))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    lvl_np = np.asarray(jax.device_get(lvl))
    rois_np = np.asarray(jax.device_get(rois))
    outs, idxs = [], []
    per_level_counts = []
    rn = (np.asarray(jax.device_get(_data(rois_num)))
          if rois_num is not None else None)
    img_of = (np.repeat(np.arange(len(rn)), rn) if rn is not None else None)
    for level in range(min_level, max_level + 1):
        sel = np.nonzero(lvl_np == level)[0]
        outs.append(Tensor(jnp.asarray(rois_np[sel])))
        idxs.append(sel)
        if rn is not None:
            # per-image roi counts at this level (reference's third output)
            per_level_counts.append(Tensor(jnp.asarray(np.bincount(
                img_of[sel], minlength=len(rn)).astype(np.int32))))
    restore = np.argsort(np.concatenate(idxs)) if idxs else np.zeros(0)
    restore_t = Tensor(jnp.asarray(restore.astype(np.int32)))
    if rn is not None:
        return outs, restore_t, per_level_counts
    return outs, restore_t


def box_clip(input, im_info, name=None):
    """Clip boxes to the image boundary (reference
    `paddle/phi/ops/yaml/ops.yaml:715` box_clip,
    `phi/kernels/cpu/box_clip_kernel.cc`): im_info rows are
    (height, width, scale); boxes live in the UN-scaled input image, so
    the limits are (dim / scale) - 1. Pure elementwise min/max —
    differentiable (clip's subgradient), vectorizes trivially."""
    def fn(b, info):
        info = info.astype(jnp.float32)
        if b.ndim != 3:
            info = info.reshape(-1)[:3]
            lim_h = info[0] / info[2] - 1.0
            lim_w = info[1] / info[2] - 1.0
        else:
            lim_h = (info[:, 0] / info[:, 2] - 1.0)[:, None, None]
            lim_w = (info[:, 1] / info[:, 2] - 1.0)[:, None, None]
        x1, y1, x2, y2 = (b[..., 0:1], b[..., 1:2], b[..., 2:3],
                          b[..., 3:4])
        zero = jnp.zeros((), b.dtype)

        def cl(v, lim):
            return jnp.maximum(jnp.minimum(v, lim.astype(b.dtype)), zero)

        return jnp.concatenate(
            [cl(x1, lim_w), cl(y1, lim_h), cl(x2, lim_w), cl(y2, lim_h)],
            axis=-1)

    return apply(fn, input, im_info, _name="box_clip")


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    """Greedy bipartite matching on a similarity matrix (reference
    `ops.yaml:620` bipartite_match, `phi/kernels/cpu/bipartite_match_kernel.cc`
    — the SSD/MultiBox target-assignment op).

    dist_matrix: [n, m] (or [B, n, m]) similarities, rows = candidates
    (e.g. ground-truth), cols = predictions (e.g. priors). Returns
    (col_to_row_match_indices, col_to_row_match_dist), each [B?, m]:
    column j's matched row (or -1) and its similarity.

    TPU-native: min(n, m) iterations of a global argmax with matched
    rows/cols masked out — a lax.fori_loop over a static bound, no
    host round trips. match_type='per_prediction' additionally matches
    every still-unmatched column to its argmax row when the similarity
    reaches dist_threshold."""
    if match_type not in ("bipartite", "per_prediction"):
        raise ValueError("match_type must be 'bipartite' or "
                         "'per_prediction'")
    d = _data(dist_matrix).astype(jnp.float32)
    batched = d.ndim == 3
    if not batched:
        d = d[None]

    B, n, m = d.shape
    NEG = jnp.float32(-1e30)

    def one(mat):
        def body(_, carry):
            work, idx, dist = carry
            flat = jnp.argmax(work)
            i, j = flat // m, flat % m
            best = work[i, j]
            ok = best > NEG / 2  # anything left to match?
            idx = jnp.where(ok, idx.at[j].set(i), idx)
            dist = jnp.where(ok, dist.at[j].set(best), dist)
            work = jnp.where(ok, work.at[i, :].set(NEG), work)
            work = jnp.where(ok, work.at[:, j].set(NEG), work)
            return work, idx, dist

        idx0 = jnp.full((m,), -1, jnp.int32)
        dist0 = jnp.zeros((m,), jnp.float32)
        work, idx, dist = jax.lax.fori_loop(
            0, min(n, m), body, (mat, idx0, dist0))
        if match_type == "per_prediction":
            cand = jnp.argmax(mat, axis=0)
            cand_d = jnp.max(mat, axis=0)
            take = (idx < 0) & (cand_d >= dist_threshold)
            idx = jnp.where(take, cand.astype(jnp.int32), idx)
            dist = jnp.where(take, cand_d, dist)
        return idx, dist

    idx, dist = jax.vmap(one)(d)
    if not batched:
        idx, dist = idx[0], dist[0]
    return Tensor(idx), Tensor(dist)


def collect_fpn_proposals(multi_rois, multi_scores, min_level=None,
                          max_level=None, post_nms_top_n=-1,
                          rois_num_per_level=None, name=None):
    """Collect proposals across FPN levels and keep the post_nms_top_n
    highest-scoring (reference `ops.yaml:971` collect_fpn_proposals,
    `phi/kernels/.../collect_fpn_proposals_kernel`): concat + one top_k —
    static shapes, single fused XLA program."""
    rois = jnp.concatenate([_data(r) for r in multi_rois], axis=0)
    scores = jnp.concatenate(
        [_data(s).reshape(-1) for s in multi_scores], axis=0)
    if rois_num_per_level is None:
        # single-image form: one global top-k on device
        k = scores.shape[0] if post_nms_top_n in (-1, None) \
            else min(int(post_nms_top_n), scores.shape[0])
        top, sel = jax.lax.top_k(scores, k)
        out = jnp.take(rois, sel, axis=0)
        return Tensor(out), Tensor(jnp.asarray([k], jnp.int32))
    # batched form: rois_num_per_level[l] is a [B] split of level l —
    # collect PER IMAGE (the reference's multi_level_rois_num path) so a
    # batch's proposals never mix; ragged packing is host-side
    per_level = [np.asarray(_data(n)).ravel() for n in rois_num_per_level]
    B = len(per_level[0])
    rois_h = np.asarray(rois, np.float32)
    sc_h = np.asarray(scores, np.float32)
    level_off = np.cumsum([0] + [int(p.sum()) for p in per_level])
    outs, counts = [], []
    for bi in range(B):
        idxs = []
        for li, p in enumerate(per_level):
            s = level_off[li] + int(p[:bi].sum())
            idxs.extend(range(s, s + int(p[bi])))
        idxs = np.asarray(idxs, np.int64)
        order = idxs[np.argsort(-sc_h[idxs])]
        if post_nms_top_n not in (-1, None):
            order = order[:int(post_nms_top_n)]
        outs.append(rois_h[order])
        counts.append(len(order))
    out = (np.concatenate(outs, axis=0) if outs
           else np.zeros((0, 4), np.float32))
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray(np.asarray(counts, np.int32))))


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode a YOLOv3 head into boxes + class scores (reference yolo_box,
    `phi/kernels/.../yolo_box_kernel`): x [B, A*(5+C), H, W] with A =
    len(anchors)//2. Returns (boxes [B, H*W*A, 4] in xyxy image coords,
    scores [B, H*W*A, C]). Pure elementwise grid math — one fused XLA
    program, no host round trip. Detections under conf_thresh get zeroed
    scores (the dense-shape analogue of the reference's filtering)."""
    xd = _data(x).astype(jnp.float32)
    im = _data(img_size).astype(jnp.float32)
    B, _, H, W = xd.shape
    A = len(anchors) // 2
    C = int(class_num)
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
    if iou_aware:
        # reference layout (yolo_box_util.h GetIoUIndex): the A iou
        # channels come FIRST, then the A*(5+C) conv channels
        iou_pred = jax.nn.sigmoid(xd[:, :A])
        feat = xd[:, A:].reshape(B, A, 5 + C, H, W)
    else:
        feat = xd.reshape(B, A, 5 + C, H, W)
    tx, ty, tw, th, tobj = (feat[:, :, 0], feat[:, :, 1], feat[:, :, 2],
                            feat[:, :, 3], feat[:, :, 4])
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    bx = (jax.nn.sigmoid(tx) * scale_x_y
          - (scale_x_y - 1) / 2 + gx) / W
    by = (jax.nn.sigmoid(ty) * scale_x_y
          - (scale_x_y - 1) / 2 + gy) / H
    input_w = W * downsample_ratio
    input_h = H * downsample_ratio
    bw = jnp.exp(tw) * an[None, :, None, None, 0] / input_w
    bh = jnp.exp(th) * an[None, :, None, None, 1] / input_h
    imh = im[:, 0][:, None, None, None]
    imw = im[:, 1][:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    obj = jax.nn.sigmoid(tobj)
    if iou_aware:
        obj = obj ** (1 - iou_aware_factor) * iou_pred ** iou_aware_factor
    cls = jax.nn.sigmoid(feat[:, :, 5:5 + C])
    scores = obj[:, :, None] * cls
    conf_mask = (obj >= conf_thresh)[:, :, None]
    scores = jnp.where(conf_mask, scores, 0.0)

    def flat(v):  # [B, A, H, W] -> [B, A*H*W]
        return v.reshape(B, A * H * W)

    boxes = jnp.stack([flat(x1), flat(y1), flat(x2), flat(y2)], axis=-1)
    sc = scores.transpose(0, 1, 3, 4, 2).reshape(B, A * H * W, C)
    return Tensor(boxes), Tensor(sc)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference matrix_nms op; SOLOv2's parallel soft-NMS):
    instead of the greedy sweep, every detection's score is decayed by its
    IoU with all higher-scored detections of the same class:
    decay = min_j f(iou_ij) / f(max_k iou_jk). Host-side output packing
    (the result count is data-dependent), matmul-style IoU matrix math."""
    b = np.asarray(_data(bboxes), np.float32)
    s = np.asarray(_data(scores), np.float32)
    B, C, N = s.shape
    outs, indices, counts = [], [], []
    for bi in range(B):
        dets = []
        for c in range(C):
            if c == background_label:
                continue
            sc = s[bi, c]
            keep = np.where(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[keep])][:nms_top_k]
            bb = b[bi, order]
            ss = sc[order]
            x1, y1, x2, y2 = bb[:, 0], bb[:, 1], bb[:, 2], bb[:, 3]
            off = 0.0 if normalized else 1.0
            area = (x2 - x1 + off) * (y2 - y1 + off)
            ix1 = np.maximum(x1[:, None], x1[None, :])
            iy1 = np.maximum(y1[:, None], y1[None, :])
            ix2 = np.minimum(x2[:, None], x2[None, :])
            iy2 = np.minimum(y2[:, None], y2[None, :])
            iw = np.maximum(ix2 - ix1 + off, 0)
            ih = np.maximum(iy2 - iy1 + off, 0)
            iou = iw * ih / np.maximum(
                area[:, None] + area[None, :] - iw * ih, 1e-10)
            iou = np.triu(iou, k=1)  # iou[i, j]: higher-scored i vs j
            comp = iou.max(axis=0)   # det i's own max overlap upstream
            if use_gaussian:
                decay = np.exp(-(iou ** 2 - comp[:, None] ** 2)
                               / gaussian_sigma)
            else:
                decay = (1 - iou) / np.maximum(1 - comp[:, None], 1e-10)
            decay = np.where(np.triu(np.ones_like(iou), k=1) > 0,
                             decay, 1.0).min(axis=0)
            new_s = ss * decay
            ok = np.where(new_s >= post_threshold)[0]
            for j in ok:
                dets.append((c, new_s[j], *bb[j], bi * C * N + c * N
                             + order[j]))
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        counts.append(len(dets))
        for d in dets:
            outs.append(d[:6])
            indices.append(d[6])
    out = (np.asarray(outs, np.float32).reshape(-1, 6) if outs
           else np.zeros((0, 6), np.float32))
    res = [Tensor(jnp.asarray(out))]
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(indices, np.int64))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(counts, np.int32))))
    return tuple(res) if len(res) > 1 else res[0]


def multiclass_nms3(bboxes, scores, rois_num=None, score_threshold=0.05,
                    nms_top_k=1000, keep_top_k=100, nms_threshold=0.3,
                    normalized=True, nms_eta=1.0, background_label=0,
                    return_index=False, name=None):
    """Per-class greedy NMS + cross-class top-k (reference multiclass_nms3,
    `phi/kernels/.../multiclass_nms3_kernel`): bboxes [B, N, 4], scores
    [B, C, N]. Returns (out [M, 6] rows (label, score, x1, y1, x2, y2),
    [index], rois_num [B]). Host-side packing like the reference CPU
    kernel; the per-class suppression reuses the device nms."""
    b = np.asarray(_data(bboxes), np.float32)
    s = np.asarray(_data(scores), np.float32)
    B, C, N = s.shape
    outs, idxs, counts = [], [], []
    for bi in range(B):
        dets = []
        for c in range(C):
            if c == background_label:
                continue
            sc = s[bi, c]
            cand = np.where(sc > score_threshold)[0]
            if cand.size == 0:
                continue
            cand = cand[np.argsort(-sc[cand])][:nms_top_k]
            kept = np.asarray(nms(Tensor(jnp.asarray(b[bi, cand])),
                                  iou_threshold=nms_threshold).numpy())
            for j in kept:
                gi = cand[int(j)]
                dets.append((c, sc[gi], *b[bi, gi], bi * N + gi))
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        counts.append(len(dets))
        for d in dets:
            outs.append(d[:6])
            idxs.append(d[6])
    out = (np.asarray(outs, np.float32).reshape(-1, 6) if outs
           else np.zeros((0, 6), np.float32))
    res = [Tensor(jnp.asarray(out))]
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(idxs, np.int64))))
    res.append(Tensor(jnp.asarray(np.asarray(counts, np.int32))))
    return tuple(res)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True, name=None):
    """RPN proposal generation (reference generate_proposals_v2,
    `phi/kernels/.../generate_proposals_kernel`): per image — decode
    anchor deltas (box_coder math), clip to the image, drop tiny boxes,
    top pre_nms_top_n by score, greedy NMS, top post_nms_top_n. Decode +
    clip run on device; the ragged packing is host-side."""
    sc = np.asarray(_data(scores), np.float32)       # [B, A, H, W]
    bd = np.asarray(_data(bbox_deltas), np.float32)  # [B, A*4, H, W]
    ims = np.asarray(_data(img_size), np.float32)    # [B, 2] (h, w)
    an = np.asarray(_data(anchors), np.float32).reshape(-1, 4)
    var = np.asarray(_data(variances), np.float32).reshape(-1, 4)
    B, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0
    all_rois, counts = [], []
    for bi in range(B):
        score = sc[bi].transpose(1, 2, 0).reshape(-1)       # H*W*A
        delta = bd[bi].reshape(A, 4, H, W).transpose(
            2, 3, 0, 1).reshape(-1, 4)
        aw = an[:, 2] - an[:, 0] + off
        ah = an[:, 3] - an[:, 1] + off
        acx = an[:, 0] + aw / 2
        acy = an[:, 1] + ah / 2
        cx = var[:, 0] * delta[:, 0] * aw + acx
        cy = var[:, 1] * delta[:, 1] * ah + acy
        w = np.exp(np.minimum(var[:, 2] * delta[:, 2], 10.0)) * aw
        h = np.exp(np.minimum(var[:, 3] * delta[:, 3], 10.0)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], axis=1)
        imh, imw = ims[bi]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, imw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, imh - off)
        ws = boxes[:, 2] - boxes[:, 0] + off
        hs = boxes[:, 3] - boxes[:, 1] + off
        valid = np.where((ws >= min_size) & (hs >= min_size))[0]
        order = valid[np.argsort(-score[valid])][:pre_nms_top_n]
        if order.size == 0:
            counts.append(0)
            continue
        kept = np.asarray(nms(Tensor(jnp.asarray(boxes[order])),
                              iou_threshold=nms_thresh).numpy())
        kept = order[kept[:post_nms_top_n]]
        all_rois.append(boxes[kept])
        counts.append(len(kept))
    rois = (np.concatenate(all_rois, axis=0) if all_rois
            else np.zeros((0, 4), np.float32))
    out = (Tensor(jnp.asarray(rois)),)
    if return_rois_num:
        out = out + (Tensor(jnp.asarray(np.asarray(counts, np.int32))),)
    return out


def psroi_pool(x, boxes, boxes_num, output_size=None, spatial_scale=1.0,
               pooled_height=1, pooled_width=1, output_channels=None,
               name=None):
    """Position-sensitive ROI pooling (reference psroi_pool,
    `phi/kernels/.../psroi_pool_kernel`; R-FCN): x [B, C, H, W] with
    C = out_c * ph * pw — output channel (i, j) bin pools its OWN channel
    group. Implemented as bin-center bilinear sampling + average (the
    PSROIAlign formulation — continuous sampling instead of the
    reference's integer binning, same capability, TPU-friendly gathers)."""
    xd = _data(x).astype(jnp.float32)
    bx = _data(boxes).astype(jnp.float32)
    bn = np.asarray(_data(boxes_num)).ravel()
    if output_size is None:
        ph, pw = int(pooled_height), int(pooled_width)
    else:
        ph, pw = ((output_size, output_size)
                  if isinstance(output_size, int) else output_size)
    B, C, H, W = xd.shape
    out_c = C // (ph * pw)
    batch_of = np.repeat(np.arange(len(bn)), bn)

    def one(box, bidx):
        x1, y1, x2, y2 = box * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1) / pw
        rh = jnp.maximum(y2 - y1, 0.1) / ph
        js, is_ = jnp.meshgrid(jnp.arange(pw, dtype=jnp.float32),
                               jnp.arange(ph, dtype=jnp.float32))
        cx = x1 + (js + 0.5) * rw   # [ph, pw] bin centers
        cy = y1 + (is_ + 0.5) * rh
        x0 = jnp.clip(jnp.floor(cx), 0, W - 1).astype(jnp.int32)
        y0 = jnp.clip(jnp.floor(cy), 0, H - 1).astype(jnp.int32)
        x1i = jnp.minimum(x0 + 1, W - 1)
        y1i = jnp.minimum(y0 + 1, H - 1)
        fx = jnp.clip(cx, 0, W - 1) - x0
        fy = jnp.clip(cy, 0, H - 1) - y0
        fm = xd[bidx].reshape(out_c, ph, pw, H, W)
        grp = fm[:, jnp.arange(ph)[:, None], jnp.arange(pw)[None, :]]
        # grp: [out_c, ph, pw, H, W]; gather the 4 corners at each bin
        g = lambda yy, xx: grp[:, is_.astype(jnp.int32), js.astype(jnp.int32),
                               yy, xx]  # noqa: E731
        v = (g(y0, x0) * (1 - fx) * (1 - fy) + g(y0, x1i) * fx * (1 - fy)
             + g(y1i, x0) * (1 - fx) * fy + g(y1i, x1i) * fx * fy)
        return v  # [out_c, ph, pw]

    outs = [one(bx[i], int(batch_of[i])) for i in range(bx.shape[0])]
    out = (jnp.stack(outs) if outs
           else jnp.zeros((0, out_c, ph, pw), jnp.float32))
    return Tensor(out)
