"""Tensor placements: Shard / Replicate / Partial.

Reference surface: `paddle/phi/core/distributed/auto_parallel/placement_types.h`
and the Python mirror `python/paddle/distributed/auto_parallel/placement_type.py`.

TPU-native design: a placement list (one entry per mesh dim) compiles directly
to a `jax.sharding.PartitionSpec` (one entry per *tensor* dim). The reference's
121 SPMD rules + reshard function library (`paddle/phi/infermeta/spmd_rules/`,
`paddle/phi/core/distributed/auto_parallel/reshard/`) collapse into GSPMD
sharding propagation: we annotate, XLA propagates and inserts collectives.

`Partial` exists transiently in the reference (a produced-but-not-yet-reduced
allreduce input, `placement_types.h` kPartial). Under a single-controller JAX
runtime an eager op over sharded operands always yields the *full* result
(XLA inserts the psum when jitted), so Partial never materializes in user
code; it is kept for API parity and for spelling reshard(p->r) explicitly.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec

__all__ = ["Placement", "Shard", "Replicate", "Partial", "to_partition_spec",
           "from_partition_spec"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    """Shard along tensor dimension `dim` over this mesh dimension."""

    def __init__(self, dim):
        self.dim = int(dim)

    def get_dim(self):
        return self.dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("Replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Pending reduction over this mesh dimension (reference kPartial)."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("Partial", self.reduce_type))

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type})"


def to_partition_spec(placements, ndim, dim_names):
    """[placement per mesh-dim] -> PartitionSpec (entry per tensor-dim).

    Multiple mesh dims sharding the same tensor dim are ordered by mesh-dim
    index (reference: `TensorDistAttr.dims_mapping` semantics,
    `paddle/phi/core/distributed/auto_parallel/dist_attr.h`).
    """
    per_tensor_dim = [[] for _ in range(ndim)]
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            if pl.dim >= ndim or pl.dim < -ndim:
                raise ValueError(
                    f"Shard(dim={pl.dim}) out of range for ndim={ndim}")
            per_tensor_dim[pl.dim % ndim].append(dim_names[mesh_dim])
    entries = []
    for axes in per_tensor_dim:
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def from_partition_spec(spec, mesh_ndim, dim_names):
    """PartitionSpec -> [placement per mesh-dim] (inverse of to_partition_spec)."""
    placements = [Replicate() for _ in range(mesh_ndim)]
    name_to_mesh_dim = {n: i for i, n in enumerate(dim_names)}
    for tdim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            placements[name_to_mesh_dim[ax]] = Shard(tdim)
    return placements
