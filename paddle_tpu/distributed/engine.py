"""Model-generic compiled parallel engine.

TPU-native counterpart of the reference auto-parallel `Engine`
(`python/paddle/distributed/auto_parallel/static/engine.py:99`) and fleet's
dygraph dispatch (`python/paddle/distributed/fleet/model.py:143-188`): takes
ANY `nn.Layer` + loss + optimizer + strategy, functionalizes the layer
(`paddle_tpu.jit.functionalize`) and builds ONE jitted XLA train step over a
`jax.sharding.Mesh`:

  - **DP**: the batch is sharded over the 'dp' mesh axis; GSPMD inserts the
    gradient all-reduce (the reference's `EagerReducer` fused allreduce,
    `reducer.cc:1089`) because parameters are replicated while data is not.
  - **ZeRO-1/2 (sharding stage 1/2)**: optimizer moments are sharded over
    'dp' along the first divisible axis (the optimizer-state partition of
    `group_sharded_optimizer_stage2.py:53`); XLA lowers the grad+update to
    reduce-scatter + sharded update + all-gather of the params.
  - **ZeRO-3 (sharding stage 3)**: parameters themselves are sharded over
    'dp' (`group_sharded_stage3.py:85`); XLA all-gathers each weight right
    before use and frees it after, like the stage-3 pre-forward hooks.
  - **TP**: an optional `mp_spec_fn(name, shape) -> PartitionSpec` annotates
    weights over the 'mp' axis; XLA's SPMD partitioner propagates the
    sharding and inserts the Megatron collectives (what the reference
    hand-writes in `mp_ops.py:77-385`).

Pipeline parallelism for the flagship model lives in the shard_map-based
`HybridParallelEngine` (hybrid_engine.py); this Engine is the breadth path —
ResNet DP, BERT ZeRO-2, any user Layer — compiled end-to-end.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["Engine"]


# --------------------------------------------------------------------------
# functional optimizers (mirror paddle_tpu.optimizer.* update rules)
# --------------------------------------------------------------------------


def _fn_sgd(hp):
    def init(params):
        return {"step": jnp.zeros((), jnp.int32)}

    def update(p, g, s, lr):
        g = g.astype(jnp.float32)
        if hp["weight_decay"]:
            g = g + hp["weight_decay"] * p.astype(jnp.float32)
        return (p.astype(jnp.float32) - lr * g).astype(p.dtype), ()

    return init, update, ()


def _fn_momentum(hp):
    def init(params):
        return {"velocity": jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params),
            "step": jnp.zeros((), jnp.int32)}

    def update(p, g, s, lr):
        (v,) = s
        g = g.astype(jnp.float32)
        if hp["weight_decay"]:
            g = g + hp["weight_decay"] * p.astype(jnp.float32)
        v = hp["momentum"] * v + g
        step = hp["momentum"] * v + g if hp["nesterov"] else v
        return (p.astype(jnp.float32) - lr * step).astype(p.dtype), (v,)

    return init, update, ("velocity",)


def _fn_adam(hp, decoupled_wd):
    b1, b2, eps = hp["beta1"], hp["beta2"], hp["epsilon"]
    wd = hp["weight_decay"]

    def init(params):
        z = lambda: jax.tree.map(  # noqa: E731
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return {"m": z(), "v": z(), "step": jnp.zeros((), jnp.int32)}

    def update(p, g, s, lr, *, step, decay=True):
        m, v = s
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        wd_p = wd if decay else 0.0
        if wd_p and not decoupled_wd:  # classic Adam L2: decay in the grad
            g32 = g32 + wd_p * p32
        m = b1 * m + (1 - b1) * g32
        v = b2 * v + (1 - b2) * (g32 * g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + eps)
        if wd_p and decoupled_wd:  # AdamW
            upd = upd + wd_p * p32
        return (p32 - lr * upd).astype(p.dtype), (m, v)

    return init, update, ("m", "v")


def shard_first_free_axis(parts, shape, degree, axis="dp"):
    """PartitionSpec sharding `axis` along the first free dim it divides —
    the numel-partition of the reference's optimizer-state/param sharding
    (`group_sharded_optimizer_stage2.py:53`) expressed as a dim split (which
    keeps XLA layouts intact). No-op if `axis` is already present or nothing
    divides."""
    parts = list(parts) + [None] * (len(shape) - len(parts))
    present = {a for p in parts if p is not None
               for a in (p if isinstance(p, (tuple, list)) else (p,))}
    if axis in present:
        return P(*parts)
    for i, (p, d) in enumerate(zip(parts, shape)):
        if p is None and d % degree == 0 and d > 0:
            parts[i] = axis
            break
    return P(*parts)


def _functionalize_optimizer(opt):
    """Map a paddle_tpu.optimizer.* instance to (init, update, slot_names).

    The eager optimizers keep per-param `_acc` slots (optimizer.py:116); this
    adapter re-expresses the same update rules as pure pytree functions for
    the compiled step.
    """
    from paddle_tpu.optimizer import SGD, Adam, AdamW, Momentum

    def hp(**kw):
        return kw

    if isinstance(opt, (Adam, AdamW)) and getattr(opt, "_multi_precision",
                                                  False):
        raise NotImplementedError(
            "Engine keeps moments in fp32 already; multi_precision master "
            "weights are not supported in the compiled step")
    if isinstance(opt, AdamW):
        if opt._lr_ratio is not None:
            raise NotImplementedError(
                "AdamW lr_ratio is not supported in the compiled Engine step")
        return _fn_adam(hp(beta1=opt._beta1, beta2=opt._beta2,
                           epsilon=opt._epsilon,
                           weight_decay=opt._wd or 0.0), True)
    if isinstance(opt, Adam):
        return _fn_adam(hp(beta1=opt._beta1, beta2=opt._beta2,
                           epsilon=opt._epsilon,
                           weight_decay=opt._weight_decay or 0.0), False)
    if isinstance(opt, Momentum):
        return _fn_momentum(hp(momentum=opt._momentum,
                               weight_decay=opt._weight_decay or 0.0,
                               nesterov=getattr(opt, "_nesterov", False)))
    if isinstance(opt, SGD):
        return _fn_sgd(hp(weight_decay=opt._weight_decay or 0.0))
    raise TypeError(
        f"Engine supports SGD/Momentum/Adam/AdamW, got {type(opt).__name__}")


def apply_optimizer_updates(params, grads, opt_state, opt_update, slots, lr,
                            decay_mask=None):
    """One functional optimizer step over a flat {name: array} tree —
    shared by the Engine and PipelineEngine compiled steps."""
    step = opt_state["step"] + 1
    new_params, new_slots = {}, {name: {} for name in slots}
    for k, p in params.items():
        s = tuple(opt_state[name][k] for name in slots)
        kw = ({"step": step, "decay": (decay_mask or {}).get(k, True)}
              if "m" in slots else {})
        np_, ns = opt_update(p, grads[k], s, lr, **kw)
        new_params[k] = np_
        for name, val in zip(slots, ns):
            new_slots[name][k] = val
    new_opt = dict(new_slots)
    new_opt["step"] = step
    return new_params, new_opt


def _functional_grad_clip(clip, clipable):
    """Pure-pytree version of Optimizer._apply_grad_clip (optimizer.py:86).
    `clipable` maps param name -> need_clip (params with need_clip=False are
    excluded, matching the eager path)."""
    if clip is None:
        return None
    from paddle_tpu import nn

    def keep(k):
        return clipable.get(k, True)

    if isinstance(clip, nn.ClipGradByGlobalNorm):
        def by_global_norm(grads):
            parts = [jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for k, g in grads.items() if keep(k)]
            if not parts:
                return grads
            total = jnp.sqrt(sum(parts))
            coef = jnp.minimum(clip.clip_norm / jnp.maximum(total, 1e-6), 1.0)
            return {k: (g * coef.astype(g.dtype)) if keep(k) else g
                    for k, g in grads.items()}

        return by_global_norm
    if isinstance(clip, nn.ClipGradByNorm):
        def by_norm(grads):
            out = {}
            for k, g in grads.items():
                if keep(k):
                    n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
                    coef = jnp.minimum(
                        clip.clip_norm / jnp.maximum(n, 1e-6), 1.0)
                    g = g * coef.astype(g.dtype)
                out[k] = g
            return out

        return by_norm
    if isinstance(clip, nn.ClipGradByValue):
        return lambda grads: {
            k: jnp.clip(g, clip.min, clip.max) if keep(k) else g
            for k, g in grads.items()}
    raise TypeError(f"unsupported grad_clip for Engine: {type(clip).__name__}")


# --------------------------------------------------------------------------
# engine
# --------------------------------------------------------------------------


class Engine:
    """Compile-and-run training/eval for any Layer over a device mesh.

    Example (config-3 shape: BERT ZeRO-2)::

        engine = Engine(model, loss=nn.CrossEntropyLoss(), optimizer=opt,
                        dp=8, sharding_stage=2)
        loss = engine.train_batch([ids], [labels])
    """

    def __init__(self, model, loss=None, optimizer=None, strategy=None,
                 dp=None, mp=1, sharding_stage=0, mesh=None, devices=None,
                 mp_spec_fn=None, seed=0, amp_level=None, amp_dtype="bfloat16",
                 remat=False, accumulate_steps=1, accumulate_avg=True):
        from paddle_tpu import jit as pjit

        self.model = model
        self.loss_layer = loss
        self.optimizer = optimizer
        # amp_level 'O1'/'O2': the forward traces under paddle_tpu.amp
        # autocast (the reference auto_parallel AMP pass, applied at trace
        # time instead of as a graph pass); loss/grads stay f32
        if amp_level not in (None, "O1", "O2", "o1", "o2"):
            raise ValueError("amp_level must be None, 'O1' or 'O2'")
        self.amp_level = amp_level.upper() if amp_level else None
        self.amp_dtype = amp_dtype
        self.remat = bool(remat)  # jax.checkpoint over the whole forward
        # gradient merge (reference auto_parallel_gradient_merge pass):
        # split the global batch into k accumulation chunks, one optimizer
        # step per train_batch
        if accumulate_steps < 1:
            raise ValueError("accumulate_steps must be >= 1")
        self.accumulate_steps = int(accumulate_steps)
        self.accumulate_avg = bool(accumulate_avg)
        if strategy is not None:  # fleet DistributedStrategy routing
            h = strategy.hybrid_configs
            if h.get("pp_degree", 1) not in (1, None):
                raise ValueError(
                    "Engine does not run pipeline parallelism; pp lives in "
                    "HybridParallelEngine (hybrid_engine.py). Set pp_degree=1 "
                    "or use the hybrid engine for the pipelined model.")
            if dp is None and h["dp_degree"] not in (-1, None):
                dp = h["dp_degree"]
            mp = h["mp_degree"] or 1
            if getattr(strategy, "sharding", False):
                sharding_stage = strategy.sharding_configs.get("stage", 1) or 1
        self.sharding_stage = sharding_stage

        if mesh is not None:
            self.mesh = mesh
        else:
            devices = devices if devices is not None else jax.devices()
            dp = dp or (len(devices) // mp)
            if dp * mp > len(devices):
                raise ValueError(f"need {dp * mp} devices, have {len(devices)}")
            self.mesh = Mesh(
                np.asarray(devices[: dp * mp]).reshape(dp, mp), ("dp", "mp"))
        self.dp = self.mesh.shape["dp"]
        self.mp = self.mesh.shape.get("mp", 1)
        self.mp_spec_fn = mp_spec_fn

        self._pure_fn, self._params, self._buffers = pjit.functionalize(model)
        self._key = jax.random.key(seed)
        if optimizer is not None:
            self._opt_init, self._opt_update, self._slots = \
                _functionalize_optimizer(optimizer)
            named = dict(model.named_parameters())
            clipable = {k: getattr(p, "need_clip", True)
                        for k, p in named.items()}
            self._grad_clip = _functional_grad_clip(optimizer._grad_clip,
                                                    clipable)
            # AdamW apply_decay_param_fun: per-param decay mask by p.name
            fn = getattr(optimizer, "_apply_decay_param_fun", None)
            self._decay_mask = {
                k: (fn(p.name) if fn is not None else True)
                for k, p in named.items()}
        self._train_step = None
        self._eval_step = None
        self._state = None  # (params, opt_state, buffers) once placed

    # -- sharding rules ------------------------------------------------------
    def _param_spec(self, name, shape):
        if self.mp_spec_fn is not None:
            spec = self.mp_spec_fn(name, shape)
            if spec is not None:
                return spec
        if self.sharding_stage >= 3:
            return self._dp_shard_spec(shape)
        return P(*([None] * len(shape)))

    def _dp_shard_spec(self, shape, base=None):
        parts = list(base) if base is not None else [None] * len(shape)
        return shard_first_free_axis(parts, shape, self.dp)

    def _slot_spec(self, pspec, shape):
        if self.sharding_stage >= 1 and self.dp > 1:
            return self._dp_shard_spec(shape, base=pspec)
        return pspec

    def _sharding(self, spec):
        return NamedSharding(self.mesh, spec)

    def _build_specs(self):
        pspecs = {k: self._param_spec(k, v.shape)
                  for k, v in self._params.items()}
        sspecs = {k: self._slot_spec(pspecs[k], v.shape)
                  for k, v in self._params.items()}
        bspecs = {k: P(*([None] * v.ndim)) for k, v in self._buffers.items()}
        return pspecs, sspecs, bspecs

    # -- state ---------------------------------------------------------------
    def _ensure_state(self):
        if self._state is not None:
            return
        pspecs, sspecs, bspecs = self._build_specs()
        self._pshard = {k: self._sharding(s) for k, s in pspecs.items()}
        self._bshard = {k: self._sharding(s) for k, s in bspecs.items()}
        params = {k: jax.device_put(v, self._pshard[k])
                  for k, v in self._params.items()}
        buffers = {k: jax.device_put(v, self._bshard[k])
                   for k, v in self._buffers.items()}
        opt_state = None
        if self.optimizer is not None:
            opt_state = self._opt_init(params)
            self._oshard = {
                name: {k: self._sharding(sspecs[k]) for k in params}
                for name in self._slots}
            self._oshard["step"] = self._sharding(P())
            opt_state = {
                name: ({k: jax.device_put(opt_state[name][k],
                                          self._oshard[name][k])
                        for k in params} if name != "step"
                       else jax.device_put(opt_state["step"],
                                           self._oshard["step"]))
                for name in list(self._slots) + ["step"]}
        self._state = [params, opt_state, buffers]

    @property
    def state(self):
        self._ensure_state()
        return self._state

    # -- steps ---------------------------------------------------------------
    def _loss_of(self, out, labels):
        from paddle_tpu.core.tensor import Tensor

        if self.loss_layer is None:
            return out if not isinstance(out, Tensor) else out._data
        t_out = jax.tree.map(
            lambda a: Tensor(a) if isinstance(a, jax.Array) else a, out)
        t_lab = [Tensor(l) for l in labels]
        loss = self.loss_layer(t_out, *t_lab)
        return loss._data if isinstance(loss, Tensor) else loss

    def _build_train_step(self):
        if self._train_step is not None:
            return self._train_step
        self._ensure_state()
        opt_update, slots = self._opt_update, self._slots
        grad_clip = self._grad_clip

        def loss_fn(params, buffers, key, inputs, labels):
            if self.amp_level:
                from paddle_tpu import amp as _amp

                with _amp.auto_cast(enable=True, level=self.amp_level,
                                    dtype=self.amp_dtype):
                    out, new_buf = self._pure_fn(params, buffers, key,
                                                 *inputs)
                    loss = self._loss_of(out, labels)
                return loss.astype(jnp.float32), new_buf
            out, new_buf = self._pure_fn(params, buffers, key, *inputs)
            return self._loss_of(out, labels), new_buf

        if self.remat:
            # strategy.recompute: rematerialize the forward in backward
            # (reference auto_parallel_recompute pass -> jax.checkpoint)
            loss_fn = jax.checkpoint(loss_fn)

        K = self.accumulate_steps

        def one_chunk(params, buffers, key, inputs, labels):
            return jax.value_and_grad(loss_fn, has_aux=True)(
                params, buffers, key, inputs, labels)

        def train_step(params, opt_state, buffers, key, lr, inputs, labels):
            if K == 1:
                (loss, new_buf), grads = one_chunk(params, buffers, key,
                                                   inputs, labels)
            else:
                # inputs/labels arrive [K, B/K, ...] (placed by train_batch)
                keys = jax.random.split(key, K)
                # accumulate in f32: summing K bf16 chunk-gradients in bf16
                # drops contributions below the running sum's ulp
                g0 = jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params)

                def body(carry, xs):
                    lacc, gacc, buf = carry
                    k, i, l = xs
                    (loss, nb), g = one_chunk(params, buf, k, i, l)
                    gacc = jax.tree.map(
                        lambda a, b: a + b.astype(jnp.float32), gacc, g)
                    return (lacc + loss, gacc, nb), None

                (lsum, gsum, new_buf), _ = jax.lax.scan(
                    body, (jnp.zeros((), jnp.float32), g0, buffers),
                    (keys, inputs, labels))
                inv = 1.0 / K
                loss = lsum * inv
                scale = inv if self.accumulate_avg else 1.0
                grads = jax.tree.map(
                    lambda p, g: (g * scale).astype(p.dtype), params, gsum)
            if grad_clip is not None:
                grads = grad_clip(grads)
            new_params, new_opt = apply_optimizer_updates(
                params, grads, opt_state, opt_update, slots, lr,
                self._decay_mask)
            return loss, new_params, new_opt, new_buf

        out_opt_shard = getattr(self, "_oshard", None)
        self._train_step = jax.jit(
            train_step,
            donate_argnums=(0, 1, 2),
            out_shardings=(None, self._pshard, out_opt_shard, self._bshard),
        )
        return self._train_step

    def _place_batch(self, arrays, micro=1):
        """Host arrays -> device arrays with the (per-chunk) batch dim
        sharded on 'dp'. micro>1 (gradient merge) reshapes [B, ...] ->
        [micro, B/micro, ...] host-side so the accumulation scan carries a
        cleanly dp-sharded chunk instead of resharding inside jit."""
        out = []
        for a in arrays:
            a = np.asarray(a.numpy() if hasattr(a, "numpy") else a)
            if a.shape[0] % (self.dp * micro) != 0:
                raise ValueError(
                    f"global batch {a.shape[0]} must divide "
                    f"dp*accumulate_steps={self.dp * micro}")
            if micro > 1:
                a = a.reshape((micro, a.shape[0] // micro) + a.shape[1:])
                spec = P(*([None, "dp"] + [None] * (a.ndim - 2)))
            else:
                spec = P(*(["dp"] + [None] * (a.ndim - 1)))
            out.append(jax.device_put(a, self._sharding(spec)))
        return out

    def train_batch(self, inputs, labels):
        """One compiled optimizer step on a global batch; returns the loss."""
        if self.optimizer is None:
            raise RuntimeError("Engine built without an optimizer")
        step = self._build_train_step()
        params, opt_state, buffers = self._state
        self._key, sub = jax.random.split(self._key)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        inputs = self._place_batch(inputs, micro=self.accumulate_steps)
        labels = self._place_batch(labels, micro=self.accumulate_steps)
        from paddle_tpu.distributed import comm_monitor as _cm

        mon = _cm.get_comm_monitor()
        if mon is not None:
            mon.check_peers()  # fail fast if a rank died between steps
        with _cm.guard("compiled_train_step"):
            loss, params, opt_state, buffers = step(
                params, opt_state, buffers, sub, lr, inputs, labels)
        self._state = [params, opt_state, buffers]
        from paddle_tpu.amp import debugging as _dbg

        if _dbg.checking_enabled():  # FLAGS_check_nan_inf post-step scan
            _dbg.assert_finite(loss, where="Engine.train_batch loss")
            _dbg.assert_finite(params, where="Engine.train_batch params")
        if hasattr(self.optimizer, "_learning_rate") and hasattr(
                self.optimizer._learning_rate, "step"):
            self.optimizer._learning_rate.step()
        return loss

    def _build_eval_step(self):
        if self._eval_step is not None:
            return self._eval_step

        def eval_step(params, buffers, key, inputs, labels):
            out, _ = self._pure_fn(params, buffers, key, *inputs)
            return self._loss_of(out, labels)

        self._eval_step = jax.jit(eval_step)
        return self._eval_step

    def eval_batch(self, inputs, labels):
        self._ensure_state()
        params, _, buffers = self._state
        step = self._build_eval_step()
        self.model.eval()
        try:
            inputs = self._place_batch(inputs)
            labels = self._place_batch(labels)
            return step(params, buffers, self._key, inputs, labels)
        finally:
            self.model.train()

    def predict_batch(self, inputs):
        self._ensure_state()
        params, _, buffers = self._state
        if not hasattr(self, "_predict_step"):
            self._predict_step = jax.jit(
                lambda p, b, k, i: self._pure_fn(p, b, k, *i)[0])
        self.model.eval()
        try:
            return self._predict_step(params, buffers, self._key,
                                      self._place_batch(inputs))
        finally:
            self.model.train()

    # -- hapi-style loop -----------------------------------------------------
    def fit(self, loader, epochs=1, log_every=0):
        """loader yields (inputs..., label) batches (paddle.io.DataLoader)."""
        losses = []
        for _ in range(epochs):
            for batch in loader:
                *inputs, label = batch
                loss = self.train_batch(inputs, [label])
                losses.append(float(jax.device_get(loss)))
                if log_every and len(losses) % log_every == 0:
                    print(f"step {len(losses)}: loss {losses[-1]:.4f}")
        return losses

    def sync_to_model(self):
        """Write the engine's (possibly sharded) params/buffers back into the
        eager Layer, gathered to host — e.g. before paddle.save."""
        self._ensure_state()
        params, _, buffers = self._state
        for k, p in self.model.named_parameters():
            p._data = jnp.asarray(jax.device_get(params[k]))
        for k, b in self.model.named_buffers():
            if k in buffers:
                b._data = jnp.asarray(jax.device_get(buffers[k]))
