"""Communication watchdog + heartbeat failure detection.

Reference counterparts:
  - `CommTaskManager` timeout loop (`paddle/phi/core/distributed/
    comm_task_manager.cc:152-168`): every collective registers a deadline;
    hung collectives are reported/aborted instead of hanging silently.
  - launch supervision / rank-death detection (`launch/controllers/
    watcher.py`, NCCL abort semantics `nccl_comm_task.cc:234-247`).

TPU-native design: XLA collectives can't be aborted mid-flight, so the
watchdog's job is *detection and loud failure*: (1) the native deadline
monitor (`csrc/watchdog.cc`) brackets eager collectives and the compiled
train step; (2) a heartbeat thread writes `hb/<rank>` to the TCPStore and
watches peers — a rank that stops heartbeating (crash, OOM, preemption) is
reported within `miss_limit * interval` seconds, turning a silent
DCN/barrier hang into an actionable error.
"""

from __future__ import annotations

import contextlib
import os
import random
import sys
import threading
import time

__all__ = ["CommMonitor", "RankFailure", "start_comm_monitor",
           "get_comm_monitor", "stop_comm_monitor", "guard",
           "retry_store_op"]

_monitor = None


class RankFailure(RuntimeError):
    pass


def retry_store_op(fn, attempts=4, base_delay=0.05, max_delay=1.0,
                   jitter=0.5, sleep=time.sleep, deadline=None):
    """Run a store get/set with exponential backoff + jitter.

    One slow KV op (store GC pause, TCP retransmit, an overloaded master)
    must not be read as a dead peer: transient failures are retried
    `attempts` times with delays base*2^i capped at `max_delay`, each
    stretched by up to `jitter` randomly (so a thundering herd of retrying
    ranks decorrelates). The LAST failure propagates — a store that is
    truly gone still fails loudly, just not on the first hiccup.

    `deadline` (time.monotonic()) hard-stops retrying: the first attempt
    always runs, but no retry starts past it — callers with their own
    cadence to keep (the heartbeat loop) bound a whole sweep this way.
    """
    attempts = max(1, attempts)  # 0/negative must still call fn once
    for i in range(attempts):
        try:
            return fn()
        except Exception:
            delay = min(max_delay, base_delay * (2 ** i)) * (
                1.0 + random.random() * jitter)
            # a retry must not START past the deadline — account for the
            # backoff sleep itself, not just time already spent
            out_of_time = (deadline is not None
                           and time.monotonic() + delay >= deadline)
            if i == attempts - 1 or out_of_time:
                raise
            sleep(delay)


class CommMonitor:
    def __init__(self, store, rank, world_size, heartbeat_interval=1.0,
                 miss_limit=5, on_failure=None, collective_timeout=300.0,
                 registry=None, store_retries=4):
        from paddle_tpu.core import native
        from paddle_tpu.observability.registry import global_registry

        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.interval = heartbeat_interval
        self.miss_limit = miss_limit
        self.collective_timeout = collective_timeout
        self.store_retries = store_retries
        self.failed_ranks = set()
        self.stale_ages = {}  # rank -> heartbeat age (s) when declared dead
        # per-rank heartbeat-age gauges land in the shared telemetry
        # registry, where TrainingMonitor.heartbeat_ages() reads them back
        self.registry = registry if registry is not None else global_registry()
        self._on_failure = on_failure
        self._stop = threading.Event()
        self._timeouts = []
        self._wd = None
        if native.available():
            self._wd = native.Watchdog(
                poll_interval=min(1.0, heartbeat_interval),
                on_timeout=self._on_wd_timeout)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- watchdog bracket for collectives / compiled steps ------------------
    def _on_wd_timeout(self, name, ms):
        msg = (f"[comm-watchdog] rank {self.rank}: '{name}' exceeded "
               f"{ms} ms — peer ranks may be dead or desynchronized "
               f"(failed so far: {sorted(self.failed_ranks) or 'none'})")
        self._timeouts.append(name)
        self.registry.inc("comm/watchdog_timeouts", labels={"op": name})
        # fault history for --telemetry-out artifacts: one counter family
        # across every failure kind, not just per-op timeout counts
        self.registry.inc("fault_events",
                          labels={"kind": "watchdog_timeout"})
        print(msg, file=sys.stderr, flush=True)

    @contextlib.contextmanager
    def guard(self, name, timeout=None):
        """Bracket a communication op with a deadline (reference CommTask
        registration around every NCCL collective)."""
        if self._wd is None:
            yield
            return
        self._wd.begin(name, timeout or self.collective_timeout)
        try:
            yield
        finally:
            self._wd.end(name)

    # -- heartbeats ----------------------------------------------------------
    def _run(self):
        # a dead rank's LAST heartbeat value stays readable in the store, so
        # liveness = "the value keeps advancing", not "the read succeeds"
        last_value = {}    # rank -> last heartbeat payload seen
        last_change = {}   # rank -> monotonic time that payload changed
        started = time.monotonic()
        grace = self.miss_limit * self.interval
        while not self._stop.is_set():
            try:
                # retried with backoff: a transiently slow store must not
                # make THIS rank look dead to its peers — but bounded to
                # half an interval, because a LONG set retry delays the
                # next write and starves our own cadence just the same
                retry_store_op(
                    lambda: self.store.set(f"hb/{self.rank}",
                                           repr(time.time())),
                    attempts=self.store_retries,
                    max_delay=self.interval / 2,
                    deadline=time.monotonic() + self.interval / 2)
            except Exception:
                pass  # the store itself died; peers will notice us missing
            self.registry.set_gauge("comm/heartbeat_age_s", 0.0,
                                    labels={"rank": self.rank})
            # the whole peer sweep shares ONE interval of retry budget: a
            # store brownout must not stretch the pass (and so THIS rank's
            # next heartbeat write) past peers' grace window — a skipped
            # read cycle is harmless, a starved own-heartbeat is not
            round_deadline = time.monotonic() + self.interval
            for r in range(self.world_size):
                if r == self.rank:
                    continue
                if r in self.failed_ranks:
                    # polling stops for dead ranks, but their age gauge
                    # keeps advancing — a frozen (or absent) gauge would
                    # read as a healthy rank instead of a dead one. Ranks
                    # that never heartbeated age from monitor start.
                    self.registry.set_gauge(
                        "comm/heartbeat_age_s",
                        time.monotonic() - last_change.get(r, started),
                        labels={"rank": r})
                    continue
                try:
                    # same backoff on reads: a slow get is NOT a missed
                    # heartbeat — only an ADVANCING-payload test (below)
                    # may declare a peer dead
                    val = retry_store_op(
                        lambda: self.store.get(f"hb/{r}", timeout=0.5),
                        attempts=self.store_retries,
                        max_delay=self.interval / 2,
                        deadline=round_deadline)
                except Exception:
                    val = None
                now = time.monotonic()
                if val is not None and val != last_value.get(r):
                    last_value[r] = val
                    last_change[r] = now
                if r in last_change:
                    stale = now - last_change[r]
                    self.registry.set_gauge("comm/heartbeat_age_s", stale,
                                            labels={"rank": r})
                    if stale > grace:
                        self._declare_dead(r, stale)
                else:
                    # never heartbeated: still export an age (from monitor
                    # start) so the rank is visible to heartbeat_ages()
                    # during the startup grace window, not only after the
                    # declare-dead below
                    self.registry.set_gauge("comm/heartbeat_age_s",
                                            now - started,
                                            labels={"rank": r})
                    if now - started > 10 * grace:
                        # never heartbeated at all (died during startup)
                        self._declare_dead(r, now - started)
            self._stop.wait(self.interval)

    def _declare_dead(self, r, stale):
        if r in self.failed_ranks:
            return
        self.failed_ranks.add(r)
        self.stale_ages[r] = stale
        self.registry.inc("comm/ranks_declared_dead")
        self.registry.inc("fault_events", labels={"kind": "dead_rank"})
        msg = (f"[comm-monitor] rank {self.rank}: rank {r} missed "
               f"heartbeats for {stale:.1f}s — declaring it DEAD")
        print(msg, file=sys.stderr, flush=True)
        if self._on_failure is not None:
            self._on_failure(r)

    def check_peers(self):
        """Raise if any peer has been declared dead (call between steps)."""
        if self.failed_ranks:
            ages = ", ".join(
                f"rank {r} last heartbeat {self.stale_ages.get(r, 0):.1f}s "
                "stale" for r in sorted(self.failed_ranks))
            raise RankFailure(
                f"rank(s) {sorted(self.failed_ranks)} are dead "
                f"(no heartbeat): {ages}; aborting per failure-detection "
                "policy")

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)
        if self._wd is not None:
            self._wd.stop()
            self._wd = None


def start_comm_monitor(store, rank, world_size, **kwargs):
    global _monitor
    if _monitor is not None:
        return _monitor
    from paddle_tpu.framework import flags as _flags

    flag = _flags.get_flags("FLAGS_heartbeat_interval_seconds").get(
        "FLAGS_heartbeat_interval_seconds") or 1.0
    interval = float(os.environ.get("PADDLE_HEARTBEAT_INTERVAL", flag))
    timeout = float(_flags.get_flags("FLAGS_distributed_timeout_seconds").get(
        "FLAGS_distributed_timeout_seconds") or 300.0)
    kwargs.setdefault("collective_timeout", timeout)
    _monitor = CommMonitor(store, rank, world_size,
                           heartbeat_interval=kwargs.pop(
                               "heartbeat_interval", interval), **kwargs)
    return _monitor


def get_comm_monitor():
    return _monitor


def stop_comm_monitor():
    global _monitor
    if _monitor is not None:
        _monitor.stop()
        _monitor = None


@contextlib.contextmanager
def guard(name, timeout=None):
    """Module-level bracket used by the functional collectives and the
    compiled engines; no-op when no monitor is running."""
    if _monitor is None:
        yield
    else:
        with _monitor.guard(name, timeout):
            yield
