"""paddle.distributed.rpc (reference: `python/paddle/distributed/rpc/rpc.py`
— init_rpc/rpc_sync/rpc_async/shutdown over a brpc master).

TPU-native design: the reference's brpc agent maps to a small per-worker
TCP server speaking length-prefixed pickled (fn, args, kwargs) frames, with
worker discovery through the framework's TCPStore rendezvous (the same
store the collective bootstrap uses, csrc/store.cc). Futures are
concurrent.futures on a client thread pool. Within one process (the
single-controller common case) calls short-circuit locally.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from collections import namedtuple
from concurrent.futures import Future, ThreadPoolExecutor

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown", "get_worker_info",
           "get_all_worker_infos", "get_current_worker_info"]

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_state = None


class _RpcState:
    def __init__(self, name, rank, world_size, store):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.store = store
        self.workers = {}
        self.server = None
        self.pool = ThreadPoolExecutor(max_workers=8)


def _serve(sock):
    while True:
        try:
            conn, _ = sock.accept()
        except OSError:
            return
        threading.Thread(target=_handle, args=(conn,), daemon=True).start()


def _recv_all(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return buf


def _handle(conn):
    try:
        while True:
            head = conn.recv(1)
            if not head:
                return
            (n,) = struct.unpack("<q", head + _recv_all(conn, 7))
            fn, args, kwargs = pickle.loads(_recv_all(conn, n))
            try:
                result = (True, fn(*args, **kwargs))
            except Exception as e:  # travels back to the caller
                result = (False, e)
            try:
                payload = pickle.dumps(result)
            except Exception as e:  # unpicklable result/exception
                payload = pickle.dumps(
                    (False, RuntimeError(
                        f"rpc result not picklable: {e!r}; original: "
                        f"{result[1]!r}")))
            conn.sendall(struct.pack("<q", len(payload)) + payload)
    except (ConnectionError, OSError):
        pass
    finally:
        conn.close()


def init_rpc(name, rank=None, world_size=None, master_endpoint=None,
             rejoin=False):
    """Start this worker's rpc agent and rendezvous with the others
    (reference rpc.py:85).

    rejoin=True: this process REPLACES a dead worker of the same rank (PS
    server failover): it re-publishes its rank's endpoint with the fresh
    port and skips the one-time init barrier (the surviving workers are
    long past it). Peers pick the new endpoint up via refresh_worker()."""
    global _state
    if _state is not None:
        raise RuntimeError("rpc already initialized")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None else rank
    world_size = (int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
                  if world_size is None else world_size)

    # Worker server. SECURITY: rpc executes pickled frames from peers, so
    # (like the reference's brpc agent) it assumes a TRUSTED network; bind
    # only the advertised interface (PADDLE_LOCAL_IP), never 0.0.0.0, to
    # keep the exposure to that network (ADVICE r2).
    ip = os.environ.get("PADDLE_LOCAL_IP")
    if not ip:
        try:
            ip = socket.gethostbyname(socket.gethostname())
        except OSError:
            ip = "127.0.0.1"
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        srv.bind((ip, 0))
    except OSError:
        # advertised IP not locally bindable (NAT): fall back to
        # all-interfaces but KEEP advertising the configured address so
        # remote peers still reach us; warn that exposure widened
        import warnings

        warnings.warn(
            f"init_rpc: PADDLE_LOCAL_IP {ip!r} is not bindable on this "
            "host; listening on 0.0.0.0 instead (rpc executes pickled "
            "frames — ensure the network is trusted)")
        srv.bind(("0.0.0.0", 0))
    srv.listen(64)
    port = srv.getsockname()[1]
    threading.Thread(target=_serve, args=(srv,), daemon=True).start()

    store = None
    if world_size > 1:
        from paddle_tpu.core.native import TCPStore

        ep = master_endpoint or os.environ.get("PADDLE_MASTER",
                                               "127.0.0.1:8711")
        host, p = ep.rsplit(":", 1)
        # PADDLE_MASTER's own port belongs to the JAX coordinator; the
        # framework's store offsets are +1 (init_parallel_env), +2
        # (elastic), +3 (rpc)
        store = TCPStore(host, int(p) + 3, is_master=(rank == 0),
                         world_size=world_size)
        store.set(f"rpc/worker/{rank}",
                  pickle.dumps(WorkerInfo(name, rank, ip, port)))

    st = _RpcState(name, rank, world_size, store)
    st.server = srv
    st.workers[name] = WorkerInfo(name, rank, ip, port)
    if store is not None:
        for r in range(world_size):
            info = pickle.loads(store.get(f"rpc/worker/{r}", timeout=60.0))
            st.workers[info.name] = info
        if not rejoin:
            store.barrier("rpc/init", rank=rank, world_size=world_size)
    _state = st
    return st


def refresh_worker(name, timeout=60.0):
    """Re-resolve a worker's endpoint from the store: a worker that died
    and was restarted (init_rpc(rejoin=True)) re-published its rank key
    with a fresh port; callers retrying a failed rpc refresh first."""
    if _state is None or _state.store is None:
        raise RuntimeError("refresh_worker needs an initialized multi-"
                           "process rpc")
    info = _state.workers.get(name)
    if info is None:
        raise ValueError(f"unknown rpc worker {name!r}")
    new = pickle.loads(_state.store.get(f"rpc/worker/{info.rank}",
                                        timeout=timeout))
    _state.workers[new.name] = new
    return new


def _call_remote(info, fn, args, kwargs, timeout):
    payload = pickle.dumps((fn, args, kwargs))
    # reference convention: timeout <= 0 means no timeout
    to = timeout if (timeout is not None and timeout > 0) else None
    with socket.create_connection((info.ip, info.port), timeout=to) as conn:
        conn.sendall(struct.pack("<q", len(payload)) + payload)
        (n,) = struct.unpack("<q", _recv_all(conn, 8))
        ok, result = pickle.loads(_recv_all(conn, n))
    if not ok:
        raise result
    return result


def _invoke(to, fn, args, kwargs, timeout):
    if _state is None:
        raise RuntimeError("call init_rpc first")
    args = args or ()
    kwargs = kwargs or {}
    info = _state.workers.get(to)
    if info is None:
        raise ValueError(f"unknown rpc worker {to!r}")
    if info.rank == _state.rank:
        return _state.pool.submit(fn, *args, **kwargs)
    return _state.pool.submit(_call_remote, info, fn, args, kwargs, timeout)


def rpc_sync(to, fn, args=None, kwargs=None, timeout=-1):
    """Blocking remote call (reference rpc.py:160)."""
    return _invoke(to, fn, args, kwargs, timeout).result(
        timeout if timeout and timeout > 0 else None)


def rpc_async(to, fn, args=None, kwargs=None, timeout=-1) -> Future:
    """Returns a Future with .wait()-compat (reference rpc.py:206)."""
    fut = _invoke(to, fn, args, kwargs, timeout)
    if not hasattr(fut, "wait"):
        fut.wait = fut.result  # reference futures expose .wait()
    return fut


def get_worker_info(name):
    if _state is None:
        raise RuntimeError("call init_rpc first")
    return _state.workers[name]


def get_all_worker_infos():
    if _state is None:
        raise RuntimeError("call init_rpc first")
    return sorted(_state.workers.values(), key=lambda w: w.rank)


def get_current_worker_info():
    if _state is None:
        raise RuntimeError("call init_rpc first")
    return _state.workers[_state.name]


def shutdown():
    """Tear down the agent (reference rpc.py barrier + stop)."""
    global _state
    if _state is None:
        return
    if _state.store is not None:
        _state.store.barrier("rpc/shutdown", rank=_state.rank,
                             world_size=_state.world_size)
        # ack phase: rank 0 HOSTS the store; if it tears the master down the
        # instant its own barrier releases, slower ranks' release polls hit
        # a dead socket and report a spurious timeout. Every rank marks the
        # release it observed; the master waits for all marks before dying.
        _state.store.set(f"rpc/shutdown_done/{_state.rank}", b"1")
        if _state.rank == 0:
            for r in range(_state.world_size):
                _state.store.get(f"rpc/shutdown_done/{r}", timeout=30.0)
    try:
        _state.server.close()
    except OSError:
        pass
    _state.pool.shutdown(wait=False)
    _state = None
