"""Ring attention: context parallelism over a mesh axis.

The reference snapshot has NO ring/context parallelism (SURVEY §2.2 —
long context there is Megatron-SP + flash attention + recompute). On TPU,
sequence scale-out beyond one chip is a first-class requirement, and the
ICI torus makes the ring pattern native: shard the sequence over a 'cp'
mesh axis, keep q resident, and rotate the k/v shards around the ring with
`ppermute` while merging per-block flash attention results with online
log-sum-exp combining ("Ring Attention with Blockwise Transformers",
Liu et al., 2023 — the public recipe; see PAPERS.md).

Non-causal: each rank does s/P x s FLOPs with one ICI hop per step, and
XLA overlaps the next ppermute with the current block's compute. Causal
with contiguous sharding is imbalanced — rank r computes r+1 of P blocks,
so lockstep wall-clock follows the last rank (~half the ring's compute
idles); zig-zag (striped) sequence sharding that gives every rank an
early+late slice is the planned fix. The per-block kernel is the
framework's Pallas flash attention (paddle_tpu/kernels/flash_attention.py)
on TPU, the fused XLA fallback elsewhere.

Use inside shard_map with the sequence dim of q/k/v sharded over
`axis_name`:

    out = ring_attention(q, k, v, axis_name="cp", causal=True)

Backward is jax AD: ppermute transposes to the reverse rotation and each
block replays through the flash kernel's custom vjp. The rotated kv shards
the scan carries are saved for backward, so per-rank residual memory is
O(s) while *compute and activations* scale as O(s/P) — the compute win of
ring attention; a recompute-in-reverse custom vjp (O(s/P) memory) is the
planned refinement.
"""

from __future__ import annotations

import jax

from paddle_tpu.distributed.mesh_utils import \
    axis_size_compat as _axis_size
import jax.numpy as jnp

__all__ = ["ring_attention", "ulysses_attention"]


def _full_block(q, k, v, fa, sm_scale, causal, interpret=False):
    b, sq, h, d = q.shape
    if (interpret or jax.default_backend() == "tpu") and fa.supports(
            q.shape, k.shape, q.dtype.itemsize):
        # differentiable (out, lse): the custom vjp folds the lse cotangent
        # from the ring merge into the flash backward's delta
        # (tests/test_flash_attention.py::test_with_lse_vjp checks the math)
        try:
            return fa.flash_attention_with_lse(q, k, v, causal, sm_scale,
                                               interpret)
        except Exception as e:  # vma-typed lowering gaps: fall back loudly
            import warnings

            warnings.warn(f"ring attention: Pallas block failed "
                          f"({type(e).__name__}: {e}); using the XLA path")
    # XLA fallback with explicit lse (GQA: repeat kv heads here; the Pallas
    # path above handles fewer kv heads natively)
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qh, kh, vh = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                        kh.astype(jnp.float32)) * sm_scale
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, -1e30)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    out = jnp.einsum("bhqk,bhkd->bhqd", (p / jnp.maximum(l, 1e-30)),
                     vh.astype(jnp.float32))
    return jnp.swapaxes(out, 1, 2).astype(q.dtype), lse


def _merge(out_a, lse_a, out_b, lse_b):
    """Combine two normalized partial attentions via log-sum-exp weights."""
    new_lse = jnp.logaddexp(lse_a, lse_b)
    wa = jnp.exp(lse_a - new_lse)[..., None]           # [b,h,sq,1]
    wb = jnp.exp(lse_b - new_lse)[..., None]
    oa = jnp.swapaxes(out_a, 1, 2).astype(jnp.float32)
    ob = jnp.swapaxes(out_b, 1, 2).astype(jnp.float32)
    merged = jnp.swapaxes(oa * wa + ob * wb, 1, 2)
    return merged.astype(out_a.dtype), new_lse


def ring_attention(q, k, v, axis_name, causal=True, sm_scale=None,
                   interpret=False):
    """q/k/v: LOCAL sequence shards [b, s_local, h(,hk), d] inside a
    shard_map over `axis_name` (P ranks; global seq = P * s_local, rank r
    holding positions [r*s_local, (r+1)*s_local))."""
    import math

    b, s_local, h, d = q.shape
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    P = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % P) for i in range(P)]

    def block(q, kk, vv, diag):
        from paddle_tpu.kernels import flash_attention as fa

        return _full_block(q, kk, vv, fa, sm_scale, causal=diag,
                           interpret=interpret)

    def step(carry, i):
        kk, vv, out, lse = carry
        # at step i this rank holds the kv shard of rank (rank - i) mod P
        src = jnp.mod(rank - i, P)

        def visible(op):
            # src == rank is the diagonal block (causal within); src < rank
            # is strictly in the past (fully visible)
            return jax.lax.cond(
                src == rank,
                lambda o: block(q, o[0], o[1], True),
                lambda o: block(q, o[0], o[1], False), op)

        def hidden(op):
            # strictly-in-the-future shard: contributes nothing; zero-scaled
            # adds keep the branch outputs' vma types identical
            tie = jnp.sum(op[0]).astype(jnp.float32) * 0
            z = jnp.zeros_like(q) + tie.astype(q.dtype)
            l = jnp.full((b, h, s_local), -1e30, jnp.float32) + tie
            return z, l

        if causal:
            blk_out, blk_lse = jax.lax.cond(src <= rank, visible, hidden,
                                            (kk, vv))
        else:
            blk_out, blk_lse = block(q, kk, vv, False)
        out, lse = _merge(out, lse, blk_out, blk_lse)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return (kk, vv, out, lse), None

    out0 = jnp.zeros_like(q)  # inherits q's cp-varying type
    # tie lse0 to q's FULL vma set (inside a hybrid mesh q may vary over
    # dp/pp too, not just the ring axis — a hard-coded pcast under-types
    # the scan carry)
    tie0 = jnp.sum(q).astype(jnp.float32) * 0
    lse0 = jnp.full((b, h, s_local), -1e30, jnp.float32) + tie0
    (_, _, out, _), _ = jax.lax.scan(step, (k, v, out0, lse0),
                                     jnp.arange(P))
    return out


def ulysses_attention(q, k, v, axis_name, causal=True, sm_scale=None,
                      interpret=False):
    """DeepSpeed-Ulysses-style sequence parallelism ("Ulysses: System
    Optimizations for Enabling Long-Sequence Transformer Training",
    Jacobs et al., 2023 — public recipe; the reference snapshot has no
    equivalent): q/k/v arrive SEQUENCE-sharded [b, s/P, h, d] over
    `axis_name`; one all_to_all re-shards them to HEAD-sharded
    [b, s, h/P, d], every rank runs ordinary (flash) attention over the
    FULL sequence for its head group, and the inverse all_to_all restores
    sequence sharding.

    vs ring attention: two all_to_alls of O(s*h/P) per call instead of P
    ppermute hops of O(s/P * h); causal balance is perfect (each rank owns
    whole heads, not sequence slices), but P must divide num_heads and
    peak activation is O(s) per rank (full-sequence attention per head
    group). Prefer ulysses when heads >> P and the ICI all_to_all is
    cheap; ring when sequence alone must scale past per-rank memory.

    Use inside shard_map with the seq dim sharded over `axis_name`:

        out = ulysses_attention(q, k, v, axis_name="sp", causal=True)

    Backward is jax AD (all_to_all transposes to the inverse all_to_all).
    """
    from paddle_tpu.kernels import flash_attention as fa
    from paddle_tpu.nn.functional.flash_attention import _sdpa_reference

    P = _axis_size(axis_name)
    h, hk = q.shape[2], k.shape[2]
    if h % P != 0 or hk % P != 0:
        raise ValueError(
            f"ulysses needs q heads ({h}) AND kv heads ({hk}) divisible by "
            f"the '{axis_name}' axis size ({P}); for GQA with few kv heads "
            "use ring_attention instead")

    def seq_to_heads(x):
        # [b, s/P, h, d] -> [b, s, h/P, d]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = None
    if (interpret or jax.default_backend() == "tpu") and fa.supports(
            qh.shape, kh.shape, qh.dtype.itemsize):
        try:
            out = fa.flash_attention_fwd(qh, kh, vh, causal=causal,
                                         scale=sm_scale,
                                         interpret=interpret)
        except Exception:  # unsupported tiling: fused-XLA fallback
            out = None
    if out is None:
        out = _sdpa_reference(qh, kh, vh, causal=causal, scale=sm_scale)
    return heads_to_seq(out)
