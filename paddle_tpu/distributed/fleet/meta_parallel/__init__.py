"""Meta-parallel wrappers (reference `fleet/meta_parallel/`).

TensorParallel / SegmentParallel / ShardingParallel wrap a model for their
axis; PipelineLayer/PipelineParallel implement stage segmentation + schedule.
Under the single-controller runtime the wrappers mainly (1) pin parameter
and input shardings onto the fleet mesh and (2) keep the reference API so
fleet scripts run unchanged.
"""

from __future__ import annotations

from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import (  # noqa: F401
    LayerDesc, SharedLayerDesc, PipelineLayer,
)
from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (  # noqa: F401
    PipelineParallel, PipelineParallelWithInterleave,
)
from paddle_tpu.distributed.fleet.layers.mpu.mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy,
)
from paddle_tpu.distributed.fleet.layers.mpu.random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed,
)

__all__ = [
    "LayerDesc", "SharedLayerDesc", "PipelineLayer", "PipelineParallel",
    "PipelineParallelWithInterleave", "VocabParallelEmbedding",
    "ColumnParallelLinear", "RowParallelLinear", "ParallelCrossEntropy",
    "TensorParallel", "SegmentParallel", "ShardingParallel",
    "RNGStatesTracker", "get_rng_state_tracker", "model_parallel_random_seed",
]


class _ParallelWrapper:
    """Shared delegation shell (reference meta_parallel/meta_parallel_base.py)."""

    def __init__(self, layers, hcg, strategy=None):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def train(self):
        self._layers.train()

    def eval(self):
        self._layers.eval()

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)


class TensorParallel(_ParallelWrapper):
    """Reference meta_parallel/tensor_parallel.py:28: broadcasts non-mp
    params inside the mp group. Single-controller params are born consistent;
    the TP layers already pinned their mp shardings at construction."""

    pass


class SegmentParallel(_ParallelWrapper):
    """Reference meta_parallel/segment_parallel.py:26: broadcast params over
    the sep group — consistent by construction here; inputs get their seq dim
    sharded over 'sep' by the compiled path."""

    pass


class ShardingParallel(_ParallelWrapper):
    """Reference meta_parallel/sharding_parallel.py: the model shell for
    group-sharded (ZeRO) training; sharding itself lives in the optimizer
    wrappers (`sharding/group_sharded.py`)."""

    pass
