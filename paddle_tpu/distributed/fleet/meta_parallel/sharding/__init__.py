"""Group-sharded (ZeRO) stages.

Reference: `fleet/meta_parallel/sharding/` —
GroupShardedOptimizerStage2 (`group_sharded_optimizer_stage2.py:53`):
optimizer-state partition; GroupShardedStage2 (`group_sharded_stage2.py:47`):
grads reduced to the owning rank per bucket; GroupShardedStage3
(`group_sharded_stage3.py:85`): parameter slicing + pre-forward allgather +
post-backward release.

TPU-native: ZeRO == weight/optimizer-state sharding over the 'sharding' mesh
axis, which XLA serves with on-demand all-gathers (stage-3) and keeps
updates local to the owning shard (stage-1/2) — the GSPMD formulation of the
same memory/communication trade. Buffer lifetime (the reference's manual
release hooks) is XLA's liveness analysis + donation in the compiled step.
"""

from __future__ import annotations

import jax

from paddle_tpu.distributed.api import shard_tensor
from paddle_tpu.distributed.placement import Replicate, Shard

__all__ = ["GroupShardedOptimizerStage2", "GroupShardedStage2",
           "GroupShardedStage3", "shard_params_over_axis",
           "shard_optimizer_state_over_axis"]


def _axis_placements(mesh, axis_name, tensor_dim):
    placements = [Replicate()] * mesh.ndim
    placements[mesh.dim_names.index(axis_name)] = Shard(tensor_dim)
    return placements


def shard_params_over_axis(layer, mesh, axis_name="sharding"):
    """Stage-3: slice every parameter over the sharding axis (largest dim,
    so slices stay MXU-tileable)."""
    degree = mesh.get_dim_size(axis_name)
    for p in layer.parameters():
        if p.ndim == 0:
            continue
        # pick the largest dim divisible by the degree
        dims = sorted(range(p.ndim), key=lambda d: -p.shape[d])
        for d in dims:
            if p.shape[d] % degree == 0:
                p._data = shard_tensor(
                    p, mesh, _axis_placements(mesh, axis_name, d))._data
                break
    return layer


def shard_optimizer_state_over_axis(optimizer, mesh, axis_name="sharding"):
    """Stage-1/2: partition accumulators over the sharding axis."""
    degree = mesh.get_dim_size(axis_name)
    accs = getattr(optimizer, "_accumulators", {})
    for key, acc in list(accs.items()):
        if hasattr(acc, "ndim") and acc.ndim >= 1 and acc.shape[0] % degree == 0:
            sharding = mesh.sharding(_axis_placements(mesh, axis_name, 0), acc.ndim)
            accs[key] = jax.device_put(acc, sharding)
    return optimizer


class GroupShardedOptimizerStage2:
    """Optimizer-state partition (reference :53). Wraps the inner optimizer;
    after each step the (lazily created) accumulators are pinned to the
    sharding axis."""

    def __init__(self, params, optim, group=None, offload=False, device="tpu",
                 **kwargs):
        self._optim = optim
        self._group = group
        self._mesh = getattr(group, "mesh", None)
        self._axis = getattr(group, "axis_name", "sharding") or "sharding"

    def __getattr__(self, name):
        return getattr(self.__dict__["_optim"], name)

    def step(self):
        self._optim.step()
        if self._mesh is not None:
            shard_optimizer_state_over_axis(self._optim, self._mesh, self._axis)

    def clear_grad(self, set_to_zero=True):
        self._optim.clear_grad(set_to_zero)

    def state_dict(self):
        return self._optim.state_dict()

    def set_state_dict(self, sd):
        return self._optim.set_state_dict(sd)


class _ShardedModelShell:
    def __init__(self, layer, optimizer=None, group=None):
        self._layers = layer
        self._optim = optimizer
        self._group = group

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def train(self):
        self._layers.train()

    def eval(self):
        self._layers.eval()

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)


class GroupShardedStage2(_ShardedModelShell):
    """Reference :47: grads owned per-rank. Under GSPMD the grad of a
    sharding-axis-sharded accumulator is reduced directly into the owning
    shard (reduce-scatter), no bucket hooks needed."""

    def __init__(self, layer, sharding_optimizer=None, group=None,
                 sync_buffers=False, buffer_max_size=2 ** 23, **kwargs):
        super().__init__(layer, sharding_optimizer, group)

    def to(self, *a, **k):
        return self


class GroupShardedStage3(_ShardedModelShell):
    """Reference :85: parameter slicing. Params are sharded over the axis at
    wrap time; XLA all-gathers at use and frees after (liveness), replacing
    the reference's _register_forward_hooks/_release machinery (:560-583)."""

    def __init__(self, layer, optimizer=None, group=None, sync_comm=False,
                 segment_size=2 ** 20, pertrain_sync_models=True, offload=False,
                 **kwargs):
        super().__init__(layer, optimizer, group)
        mesh = getattr(group, "mesh", None)
        if mesh is not None:
            axis = getattr(group, "axis_name", "sharding") or "sharding"
            shard_params_over_axis(layer, mesh, axis)

    def get_all_parameters(self, convert2cpu=False):
        """Reference: gather full params (e.g. before save)."""
        from paddle_tpu.distributed.api import unshard_dtensor

        for p in self._layers.parameters():
            p._data = unshard_dtensor(p)._data
