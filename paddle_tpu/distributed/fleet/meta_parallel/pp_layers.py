"""PipelineLayer: declarative stage segmentation.

Reference: `python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py`
— LayerDesc (:57), SharedLayerDesc (:77), PipelineLayer (:264) which cuts the
layer list into pp_degree segments (uniform or by seg_method) and
instantiates only the local stage's layers.

TPU-native: the single controller owns every stage, so PipelineLayer
instantiates *all* segments and records the stage boundaries. The eager
trainer runs them in order (mathematically identical to 1F1B — see
pipeline_parallel.py); the compiled trainer consumes `self.segments` to
build the stage-sharded scan/ppermute pipeline over the 'pp' mesh axis.
"""

from __future__ import annotations

import re

from paddle_tpu import nn

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_class, *inputs, **kwargs):
        self.layer_class = layer_class
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_class, nn.Layer):
            raise TypeError(f"{layer_class} must be a paddle.nn.Layer subclass")

    def build_layer(self):
        return self.layer_class(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_class.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing in several stages (reference :77, e.g.
    tied input/output embeddings)."""

    def __init__(self, key, layer_class, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_class, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(nn.Layer):
    """Reference pp_layers.py:264."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._topo = topology
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = num_stages or 1

        # build all layers (single controller owns all stages)
        self.run_function = []
        self._shared = {}
        built = nn.LayerList()
        for i, d in enumerate(self._layers_desc):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared:
                    self._shared[d.layer_name] = d.build_layer()
                layer = self._shared[d.layer_name]
                fwd = d.forward_func
                if fwd is not None:
                    self.run_function.append(
                        (lambda l, f: (lambda *x: f(l, *x)))(layer, fwd))
                else:
                    self.run_function.append(layer)
                built.append(layer)
            elif isinstance(d, LayerDesc):
                layer = d.build_layer()
                self.run_function.append(layer)
                built.append(layer)
            elif isinstance(d, nn.Layer):
                self.run_function.append(d)
                built.append(d)
            elif callable(d):
                self.run_function.append(d)
            else:
                raise TypeError(f"unsupported layer desc {d!r}")
        self._built_layers = built

        self.segments = self._segment(seg_method)

    def _segment(self, seg_method):
        """Cut run_function into num_stages segments: 'uniform' or
        'layer:<ClassName>' (reference SegmentLayers)."""
        n = len(self.run_function)
        k = self._num_stages
        if isinstance(seg_method, str) and seg_method.startswith("layer:"):
            cls_name = seg_method.split(":", 1)[1]
            marks = [i for i, d in enumerate(self._layers_desc)
                     if (isinstance(d, LayerDesc) and
                         d.layer_class.__name__ == cls_name)
                     or type(d).__name__ == cls_name]
            if len(marks) >= k:
                per = len(marks) // k
                cuts = [0] + [marks[per * i] for i in range(1, k)] + [n]
            else:
                cuts = self._uniform_cuts(n, k)
        else:
            cuts = self._uniform_cuts(n, k)
        return [(cuts[i], cuts[i + 1]) for i in range(k)]

    @staticmethod
    def _uniform_cuts(n, k):
        base, rem = divmod(n, k)
        cuts = [0]
        for i in range(k):
            cuts.append(cuts[-1] + base + (1 if i < rem else 0))
        return cuts

    def get_num_stages(self):
        return self._num_stages

    def stage_forward(self, stage_id, *args):
        """One stage's segment (the eager 1F1B scheduler's unit of work),
        honoring recompute_interval exactly like forward() — the eager
        trainer's activation-memory bound rides on it."""
        start, end = self.segments[stage_id]
        x = args
        for i in range(start, end):
            fn = self.run_function[i]
            if self._recompute_interval > 0 and \
                    i % self._recompute_interval == 0 and i > 0:
                from paddle_tpu.distributed.fleet.recompute import recompute

                x = (recompute(fn, *x) if isinstance(x, tuple)
                     else recompute(fn, x))
            else:
                x = fn(*x) if isinstance(x, tuple) else fn(x)
        return x

    def forward(self, *args):
        x = args
        for i, fn in enumerate(self.run_function):
            if self._recompute_interval > 0 and i % self._recompute_interval == 0 \
                    and i > 0:
                from paddle_tpu.distributed.fleet.recompute import recompute

                x = (recompute(fn, *x) if isinstance(x, tuple)
                     else recompute(fn, x))
            else:
                x = fn(*x) if isinstance(x, tuple) else fn(x)
        return x
