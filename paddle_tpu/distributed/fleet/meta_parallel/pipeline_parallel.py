"""PipelineParallel trainer (1F1B semantics).

Reference: `python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py`
— `train_batch` (:940) splits the batch into micro-batches and runs
`forward_backward_pipeline` (:684): 1F1B warmup/steady/cooldown with p2p
isend/irecv at stage edges (`pp_utils/p2p_communication.py:573`).

TPU-native: 1F1B exists to bound activation memory *per rank process*; its
loss/grad math is exactly gradient accumulation over micro-batches. Under a
single controller the eager trainer runs micro-batches through all stages in
order and accumulates grads — bit-identical losses to the reference schedule
— while the *performance* schedules (stage-sharded scan + collective-permute
over the 'pp' mesh axis, riding ICI) live in the compiled paths:
`paddle_tpu.distributed.hybrid_engine.HybridParallelEngine` (flagship
Llama, gpipe/1f1b/VPP/zero-bubble) and
`paddle_tpu.distributed.pipeline_engine.PipelineEngine` (any
PipelineLayer). Activation memory in eager is bounded by recompute_interval.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


class PipelineParallel:
    def __init__(self, layers, hcg, strategy):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pp_cfg = strategy.pipeline_configs
        self.micro_batch_size = pp_cfg.get("micro_batch_size", 1)
        self.accumulate_steps = pp_cfg.get("accumulate_steps", 1)
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.total_loss = None

    # -- Layer delegation ----------------------------------------------------
    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def train(self):
        self._layers.train()

    def eval(self):
        self._layers.eval()

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    # -- the schedule --------------------------------------------------------
    def _split_micro(self, data):
        """Split [B, ...] inputs into accumulate_steps micro-batches."""
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d) for d in data]
            return list(zip(*parts))
        if isinstance(data, Tensor):
            n = self.accumulate_steps
            b = data.shape[0]
            if b % n != 0:
                raise ValueError(
                    f"batch size {b} not divisible by accumulate_steps {n}")
            mb = b // n
            return [data[i * mb:(i + 1) * mb] for i in range(n)]
        return [data] * self.accumulate_steps

    def forward_backward_pipeline(self, data, scaler=None):
        """Micro-batch loop == 1F1B loss/grad math (reference :684)."""
        inputs, labels = data
        micro_inputs = self._split_micro(inputs)
        micro_labels = self._split_micro(labels)
        total = None
        for mi, ml in zip(micro_inputs, micro_labels):
            out = self._layers(mi) if not isinstance(mi, (tuple, list)) \
                else self._layers(*mi)
            loss_fn = getattr(self._layers, "_loss_fn", None)
            if loss_fn is None:
                raise RuntimeError("PipelineLayer needs loss_fn for train_batch")
            loss = loss_fn(out, ml)
            loss = loss / self.accumulate_steps
            if scaler is not None:
                scaled = scaler.scale(loss)
                scaled.backward()
            else:
                loss.backward()
            total = loss if total is None else total + loss.detach()
        self.total_loss = total
        return total

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference :940: run schedule then step."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        from paddle_tpu.core.tensor import no_grad

        inputs, labels = data
        with no_grad():
            out = self._layers(inputs) if not isinstance(inputs, (tuple, list)) \
                else self._layers(*inputs)
            if compute_loss:
                return self._layers._loss_fn(out, labels)
            return out


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved (virtual) pipeline, reference :1308.

    Under the single controller the loss/grad math is identical to 1F1B
    (gradient accumulation), but this class carries the interleave *config* —
    virtual stage count, chunk segmentation, and the schedule tag the
    compiled path consumes (`HybridParallelEngine(schedule="interleave")`,
    hybrid_engine.py `_pipeline_loss_vpp`). It validates the same invariants
    the reference enforces (accumulate_steps % num_stages, chunk count
    dividing the layer segments). The loss/grad math itself is inherited
    micro-batch accumulation — chunk interleaving is realized on the mesh by
    the compiled schedule, not re-enacted per-op here.
    """

    schedule = "interleave"

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        self.num_model_chunks = getattr(
            layers, "_num_virtual_pipeline_stages", None) or \
            strategy.pipeline_configs.get("vpp_degree", 2)
        if self.num_model_chunks < 2:
            raise ValueError(
                "interleaved pipeline needs >= 2 virtual stages per rank "
                "(reference pipeline_parallel.py:1322)")
        if self.accumulate_steps % max(self.num_stages, 1) != 0:
            raise ValueError(
                "accumulate_steps must be divisible by the pipeline degree "
                "for the interleaved schedule (reference :1330)")
        segments = getattr(layers, "_segments", None)
        if segments is not None and len(segments) % self.num_model_chunks:
            raise ValueError(
                f"number of layer segments ({len(segments)}) must be a "
                f"multiple of num_model_chunks ({self.num_model_chunks})")

    def forward_backward_pipeline(self, data, scaler=None):
        # same accumulation math; chunk interleaving is a per-rank execution
        # order concern that the compiled schedule realizes on the mesh
        return super().forward_backward_pipeline(data, scaler)
