"""PipelineParallel trainer: a REAL eager 1F1B scheduler.

Reference: `python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py`
— `train_batch` (:940) splits the batch into micro-batches and runs
`forward_backward_pipeline` (:684): 1F1B warmup/steady/cooldown with p2p
isend/irecv at stage edges (`pp_utils/p2p_communication.py:573`).

TPU-native: the *performance* schedules (stage-sharded scan +
collective-permute over the 'pp' mesh axis, riding ICI) live in the
compiled paths (`HybridParallelEngine`, `PipelineEngine`). This eager
trainer exists for what the reference's eager mode is for — DEBUGGING the
schedule mechanics — so it runs the actual per-stage state machine, not
just gradient accumulation (the r3/r4 shape of this file): stage-local
segments exchange detached boundary activations forward and boundary
grads backward through queues, each stage obeys the 1F1B in-flight bound
(<= S - s stashed activations, the schedule's entire memory point, which
`max_inflight` exposes for inspection), and backward re-enters the stage
subgraph via `autograd.backward(outputs, output_grads)`. Loss/grad math
is identical to the reference schedule; per-stage order is too.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


class PipelineParallel:
    def __init__(self, layers, hcg, strategy):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pp_cfg = strategy.pipeline_configs
        self.micro_batch_size = pp_cfg.get("micro_batch_size", 1)
        self.accumulate_steps = pp_cfg.get("accumulate_steps", 1)
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.total_loss = None

    # -- Layer delegation ----------------------------------------------------
    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def train(self):
        self._layers.train()

    def eval(self):
        self._layers.eval()

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    # -- the schedule --------------------------------------------------------
    def _split_micro(self, data):
        """Split [B, ...] inputs into accumulate_steps micro-batches."""
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d) for d in data]
            return list(zip(*parts))
        if isinstance(data, Tensor):
            n = self.accumulate_steps
            b = data.shape[0]
            if b % n != 0:
                raise ValueError(
                    f"batch size {b} not divisible by accumulate_steps {n}")
            mb = b // n
            return [data[i * mb:(i + 1) * mb] for i in range(n)]
        return [data] * self.accumulate_steps

    def forward_backward_pipeline(self, data, scaler=None):
        """The 1F1B state machine (reference :684): per-stage warmup /
        steady 1F1B / cooldown over boundary-activation queues, with the
        schedule's in-flight bound enforced (stage s stashes at most
        S - s activations)."""
        from paddle_tpu import autograd as _autograd

        inputs, labels = data
        M = self.accumulate_steps
        S = self.num_stages
        micro_inputs = self._split_micro(inputs)
        micro_labels = self._split_micro(labels)
        loss_fn = getattr(self._layers, "_loss_fn", None)
        if loss_fn is None:
            raise RuntimeError("PipelineLayer needs loss_fn for train_batch")
        if not hasattr(self._layers, "stage_forward"):
            raise RuntimeError("PipelineParallel needs a PipelineLayer "
                               "(stage segments)")

        in_q = [deque() for _ in range(S)]    # boundary acts from s-1
        grad_q = [deque() for _ in range(S)]  # boundary grads from s+1
        stash = [deque() for _ in range(S)]   # (boundary_in, out) per mb
        fwd_done = [0] * S
        bwd_done = [0] * S
        self.max_inflight = [0] * S
        losses = []
        # warmup depth: stage s runs S-1-s forwards before its first
        # backward (reference :684's num_warmup_microbatches)
        warmup = [min(S - 1 - s, M) for s in range(S)]

        def as_tuple(x):
            return x if isinstance(x, tuple) else (x,)

        def do_fwd(s):
            mb = fwd_done[s]
            if s == 0:
                x = micro_inputs[mb]
                xs = tuple(x) if isinstance(x, (tuple, list)) else (x,)
                boundary = None
            else:
                xs = as_tuple(in_q[s].popleft())
                # the stage boundary: detached leaves that collect the
                # incoming grad for the p2p hop backward
                xs = tuple(t.detach() for t in xs)
                for t in xs:
                    t.stop_gradient = False
                boundary = xs
            out = self._layers.stage_forward(s, *xs)
            fwd_done[s] += 1
            if s == S - 1:
                loss = loss_fn(out, micro_labels[mb]) / M
                losses.append(loss)
                stash[s].append((boundary, loss))
            else:
                stash[s].append((boundary, out))
                outs = as_tuple(out)
                nxt = tuple(t.detach() for t in outs)
                in_q[s + 1].append(nxt if len(nxt) > 1 else nxt[0])
            self.max_inflight[s] = max(self.max_inflight[s],
                                       len(stash[s]))

        def do_bwd(s):
            boundary, out = stash[s].popleft()
            if s == S - 1:
                if scaler is not None:
                    scaler.scale(out).backward()
                else:
                    out.backward()
            else:
                gs = as_tuple(grad_q[s].popleft())
                _autograd.backward(list(as_tuple(out)), list(gs))
            bwd_done[s] += 1
            if s > 0:
                # a pass-through boundary tensor the loss doesn't depend on
                # gets a ZERO grad, like the reference's p2p of zeroed
                # buffers — None would crash the upstream backward
                import jax.numpy as jnp

                grads = tuple(
                    t.grad if t.grad is not None
                    else Tensor(jnp.zeros_like(t._data))
                    for t in boundary)
                grad_q[s - 1].append(grads if len(grads) > 1
                                     else grads[0])

        def can_fwd(s):
            if fwd_done[s] >= M:
                return False
            return s == 0 or len(in_q[s]) > 0

        def can_bwd(s):
            if bwd_done[s] >= fwd_done[s] or not stash[s]:
                return False
            return s == S - 1 or len(grad_q[s]) > 0

        while any(b < M for b in bwd_done):
            progressed = False
            for s in range(S):
                if fwd_done[s] < warmup[s] and can_fwd(s):
                    do_fwd(s)          # warmup: forwards only
                    progressed = True
                elif can_bwd(s):
                    do_bwd(s)          # steady: backward has priority
                    progressed = True
                elif can_fwd(s) and len(stash[s]) < S - s:
                    do_fwd(s)          # 1F1B in-flight bound
                    progressed = True
            if not progressed:
                raise RuntimeError(
                    f"pipeline schedule deadlock: fwd={fwd_done} "
                    f"bwd={bwd_done}")

        total = losses[0].detach()
        for l in losses[1:]:
            total = total + l.detach()
        self.total_loss = total
        return total

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference :940: run schedule then step."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        from paddle_tpu.core.tensor import no_grad

        inputs, labels = data
        with no_grad():
            out = self._layers(inputs) if not isinstance(inputs, (tuple, list)) \
                else self._layers(*inputs)
            if compute_loss:
                return self._layers._loss_fn(out, labels)
            return out


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved (virtual) pipeline, reference :1308.

    Under the single controller the loss/grad math is identical to 1F1B
    (gradient accumulation), but this class carries the interleave *config* —
    virtual stage count, chunk segmentation, and the schedule tag the
    compiled path consumes (`HybridParallelEngine(schedule="interleave")`,
    hybrid_engine.py `_pipeline_loss_vpp`). It validates the same invariants
    the reference enforces (accumulate_steps % num_stages, chunk count
    dividing the layer segments). The loss/grad math itself is inherited
    micro-batch accumulation — chunk interleaving is realized on the mesh by
    the compiled schedule; the eager loss/grad math (the inherited 1F1B
    state machine) is chunk-order independent.
    """

    schedule = "interleave"

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        self.num_model_chunks = getattr(
            layers, "_num_virtual_pipeline_stages", None) or \
            strategy.pipeline_configs.get("vpp_degree", 2)
        if self.num_model_chunks < 2:
            raise ValueError(
                "interleaved pipeline needs >= 2 virtual stages per rank "
                "(reference pipeline_parallel.py:1322)")
        if self.accumulate_steps % max(self.num_stages, 1) != 0:
            raise ValueError(
                "accumulate_steps must be divisible by the pipeline degree "
                "for the interleaved schedule (reference :1330)")
        segments = getattr(layers, "_segments", None)
        if segments is not None and len(segments) % self.num_model_chunks:
            raise ValueError(
                f"number of layer segments ({len(segments)}) must be a "
                f"multiple of num_model_chunks ({self.num_model_chunks})")

    def forward_backward_pipeline(self, data, scaler=None):
        # same 1F1B machinery; chunk interleaving is a per-rank execution
        # order concern that the compiled schedule realizes on the mesh
        return super().forward_backward_pipeline(data, scaler)
