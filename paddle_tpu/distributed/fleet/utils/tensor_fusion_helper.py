"""Tensor fusion utilities (reference:
`python/paddle/distributed/fleet/utils/tensor_fusion_helper.py` — flattens
parameter/gradient groups into contiguous buffers so one collective moves a
whole bucket, `:330` fused reduce-scatter, `:755` fused allreduce).

TPU-native role: XLA already fuses and schedules collectives, so fusion is
not needed for comm efficiency on the compiled path. The API remains useful
for (a) bucketing parameters by byte size (the grouping logic schedulers
reason about), and (b) flat views for checkpoint compaction and host-side
transfers — so it is implemented for real over jnp, not stubbed.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["flatten_dense_tensors", "obtain_storage", "fused_parameters",
           "HOOK_ACTION", "GradStorage", "assign_group_by_size"]


class HOOK_ACTION:
    ALL_REDUCE = 0
    REDUCE = 1
    REDUCE_SCATTER = 2


def _nbytes(t):
    d = t._data if isinstance(t, Tensor) else t
    return d.size * d.dtype.itemsize


def assign_group_by_size(parameters, group_size=128 * 1024 * 1024):
    """Bucket params into groups of ~group_size bytes, preserving order
    (reference assign_group_by_size / EagerReducer bucketing)."""
    groups, cur, cur_bytes = [], [], 0
    for p in parameters:
        cur.append(p)
        cur_bytes += _nbytes(p)
        if cur_bytes >= group_size:
            groups.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        groups.append(cur)
    return groups


def flatten_dense_tensors(parameters, dtype=None):
    """Concatenate a group into one flat buffer; returns (flat, specs)
    where specs = [(shape, size), ...] recover the views."""
    datas = [p._data if isinstance(p, Tensor) else jnp.asarray(p)
             for p in parameters]
    dt = dtype or datas[0].dtype
    flat = jnp.concatenate([d.astype(dt).ravel() for d in datas])
    specs = [(tuple(d.shape), int(d.size)) for d in datas]
    return Tensor(flat), specs


def split_flat_tensor(flat, specs):
    """Inverse of flatten_dense_tensors."""
    data = flat._data if isinstance(flat, Tensor) else flat
    out, off = [], 0
    for shape, size in specs:
        out.append(Tensor(data[off:off + size].reshape(shape)))
        off += size
    return out


class GradStorage:
    """A fused gradient bucket (reference GradStorage): accumulate member
    grads, read back the flat buffer, scatter updates to members."""

    def __init__(self, parameters, dtype=None):
        self.params = list(parameters)
        # np.prod(()) == 1 covers scalars; zero-element params keep size 0
        self.specs = [(tuple(p.shape), int(np.prod(p.shape)))
                      for p in self.params]
        self.dtype = dtype
        self._flat = None

    def pack_grads(self):
        grads = []
        for p, (shape, size) in zip(self.params, self.specs):
            g = p.grad
            if g is None:
                grads.append(jnp.zeros(shape, p._data.dtype))
            else:
                grads.append(g._data if isinstance(g, Tensor) else g)
        self._flat, _ = flatten_dense_tensors(
            [Tensor(g) if not isinstance(g, Tensor) else g for g in grads],
            self.dtype)
        return self._flat

    def unpack_to_grads(self, flat=None):
        flat = flat if flat is not None else self._flat
        for p, t in zip(self.params, split_flat_tensor(flat, self.specs)):
            p.grad = Tensor(t._data.astype(p._data.dtype))


def obtain_storage(parameters, dtype=None, group_size=128 * 1024 * 1024,
                   **kwargs):
    """Group params and build a GradStorage per bucket (reference
    obtain_storage)."""
    return [GradStorage(g, dtype) for g in
            assign_group_by_size(parameters, group_size)]


def fused_parameters(parameters, use_main_grad=False, fuse_param=True,
                     comm_overlap=False, comm_group=None, dst=-1,
                     acc_step=1, scale_after_comm=False,
                     group_size=128 * 1024 * 1024, **kwargs):
    """Reference fused_parameters entry: returns (decay_fused, all_fused,
    all_buffers). On this stack the buffers exist for bucketing/packing;
    the collective fusion itself is XLA's job."""
    storages = obtain_storage(parameters, group_size=group_size)
    return storages, storages, storages
