"""Megatron-style sequence parallelism utilities.

Reference: `python/paddle/distributed/fleet/utils/sequence_parallel_utils.py`
— ScatterOp/GatherOp/AllGatherOp/ReduceScatterOp PyLayers (:85-147),
ColumnSequenceParallelLinear (:429), RowSequenceParallelLinear (:564):
activations sharded along the *sequence* dim across the TP group between the
attention/MLP blocks, so LayerNorm/dropout compute on seq/tp_degree tokens.

TPU-native: sequence sharding is just a sharding constraint on the seq dim
over the 'mp' axis; XLA places the all-gather before the column matmul and
the reduce-scatter after the row matmul — exactly the reference's manual
schedule, but fused and overlapped by the compiler. The PyLayer forms below
exist so eager code (and tests) can spell the transitions explicitly.
"""

from __future__ import annotations

from paddle_tpu.distributed.api import shard_tensor
from paddle_tpu.distributed.placement import Replicate, Shard
from paddle_tpu.distributed.fleet.layers.mpu.mp_layers import (
    ColumnParallelLinear, RowParallelLinear,
)

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "scatter", "all_gather",
    "mark_as_sequence_parallel_parameter",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
    "create_fused_allreduce_gradient_hooks",
]


def _mp_mesh():
    from paddle_tpu.distributed import fleet

    hcg = fleet.get_hybrid_communicate_group()
    if hcg is None:
        return None, -1
    return hcg.mesh, hcg.mesh.dim_names.index("mp")


def _seq_placements(mesh, mp_idx, seq_dim):
    placements = [Replicate()] * mesh.ndim
    placements[mp_idx] = Shard(seq_dim)
    return placements


def scatter(x, seq_dim=0):
    """Split the seq dim across the TP group (reference :85 ScatterOp fwd)."""
    mesh, mp_idx = _mp_mesh()
    if mesh is None:
        return x
    return shard_tensor(x, mesh, _seq_placements(mesh, mp_idx, seq_dim),
                        stop_gradient=x.stop_gradient)


def all_gather(x, seq_dim=0):
    """Gather the seq dim back (reference :103 GatherOp fwd)."""
    mesh, mp_idx = _mp_mesh()
    if mesh is None:
        return x
    return shard_tensor(x, mesh, [Replicate()] * mesh.ndim,
                        stop_gradient=x.stop_gradient)


class ScatterOp:
    """seq split fwd / all-gather bwd — the transition into an SP region."""

    @staticmethod
    def apply(x, seq_dim=0):
        return scatter(x, seq_dim)


class GatherOp:
    """all-gather fwd / seq split bwd — the transition out of an SP region."""

    @staticmethod
    def apply(x, seq_dim=0):
        return all_gather(x, seq_dim)


class AllGatherOp:
    """all-gather fwd / reduce-scatter bwd (before ColumnSPLinear)."""

    @staticmethod
    def apply(x, seq_dim=0):
        return all_gather(x, seq_dim)


class ReduceScatterOp:
    """reduce-scatter fwd / all-gather bwd (after RowSPLinear)."""

    @staticmethod
    def apply(x, seq_dim=0):
        return scatter(x, seq_dim)


def mark_as_sequence_parallel_parameter(param):
    param.sequence_parallel = True if not hasattr(param, "__slots__") else None


def create_fused_allreduce_gradient_hooks(model, accumulation_steps=1):
    """Reference :156-217: SP params need grad allreduce over mp. Grads are
    globally exact under the single controller — nothing to register."""
    return []


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Reference :429: AllGather(seq) -> column-parallel matmul."""

    def forward(self, x):
        x = AllGatherOp.apply(x, seq_dim=1 if x.ndim >= 3 else 0)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Reference :564: row-parallel matmul -> ReduceScatter(seq)."""

    def forward(self, x):
        out = super().forward(x)
        return ReduceScatterOp.apply(out, seq_dim=1 if out.ndim >= 3 else 0)
