"""fleet.utils (reference fleet/utils/)."""

from paddle_tpu.distributed.fleet.utils import fs  # noqa: F401
from paddle_tpu.distributed.fleet.utils import sequence_parallel_utils  # noqa: F401
from paddle_tpu.distributed.fleet.utils import tensor_fusion_helper  # noqa: F401
from paddle_tpu.distributed.fleet.utils import timer_helper  # noqa: F401
from paddle_tpu.distributed.fleet.recompute import recompute  # noqa: F401
from paddle_tpu.distributed.fleet.utils.fs import HDFSClient, LocalFS  # noqa: F401
from paddle_tpu.distributed.fleet.utils.timer_helper import (  # noqa: F401
    get_timers, set_timers,
)
