"""Distributed filesystem clients (reference:
`python/paddle/distributed/fleet/utils/fs.py` — LocalFS + HDFSClient over
the hadoop CLI, used by checkpoint save/load on shared storage)."""

from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError"]


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """reference fs.py LocalFS — full local implementation."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            if os.path.isdir(os.path.join(fs_path, name)):
                dirs.append(name)
            else:
                files.append(name)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def mv(self, src, dst, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src):
            raise FSFileNotExistsError(src)
        if not overwrite and self.is_exist(dst):
            raise FSFileExistsError(dst)
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        shutil.move(src, dst)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        open(fs_path, "a").close()

    def upload(self, local_path, fs_path):
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path)
        else:
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient(FS):
    """reference fs.py HDFSClient — shells out to the hadoop CLI. Raises a
    clear error when hadoop is not installed (no silent stubbing)."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else "hadoop"
        self._configs = []
        for k, v in (configs or {}).items():
            self._configs += ["-D", f"{k}={v}"]
        self._timeout = time_out

    def _run(self, *args, check=True):
        cmd = [self._hadoop, "fs"] + self._configs + list(args)
        try:
            return subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=self._timeout, check=check)
        except FileNotFoundError as e:
            raise RuntimeError(
                "HDFSClient requires the hadoop CLI on PATH (or pass "
                "hadoop_home); it is not installed here") from e

    def is_exist(self, fs_path):
        return self._run("-test", "-e", fs_path, check=False).returncode == 0

    def is_file(self, fs_path):
        return self._run("-test", "-f", fs_path, check=False).returncode == 0

    def is_dir(self, fs_path):
        return self._run("-test", "-d", fs_path, check=False).returncode == 0

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path, check=False).stdout.splitlines()
        dirs, files = [], []
        for line in out:
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path, check=False)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mv(self, src, dst, overwrite=False):
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        self._run("-mv", src, dst)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        self._run("-touchz", fs_path)
