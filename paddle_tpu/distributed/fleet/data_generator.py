"""PS data generators (reference `python/paddle/distributed/fleet/
data_generator/data_generator.py`): user subclasses implement
generate_sample; these classes frame each sample into the MultiSlot text
protocol the reference's Dataset/DataFeed readers consume
(`slot_num value... slot_num value...`)."""

from __future__ import annotations

import sys

__all__ = ["MultiSlotDataGenerator", "MultiSlotStringDataGenerator"]


class _DataGeneratorBase:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        """User hook: return a generator yielding
        [(slot_name, [values...]), ...] per sample."""
        raise NotImplementedError(
            "subclasses must implement generate_sample(line)")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s

        return local_iter

    def _format(self, sample):
        raise NotImplementedError

    def run_from_stdin(self):
        for line in sys.stdin:
            g = self.generate_sample(line)
            if g is None:
                continue
            for sample in g():
                sys.stdout.write(self._format(sample))

    def run_from_memory(self, lines=None):
        """Return framed strings instead of writing stdout (test/loader
        path)."""
        out = []
        for line in (lines if lines is not None else [None]):
            g = self.generate_sample(line)
            if g is None:
                continue
            for sample in g():
                out.append(self._format(sample))
        return out


class MultiSlotDataGenerator(_DataGeneratorBase):
    """Values are numbers; each slot framed as `<n> v1 ... vn`."""

    def _format(self, sample):
        if not isinstance(sample, (list, tuple)) or not sample:
            raise ValueError(
                "generate_sample must yield a non-empty list of "
                "(slot_name, values) pairs")
        parts = []
        names = []
        for name, values in sample:
            if not isinstance(values, (list, tuple)) or not values:
                raise ValueError(f"slot {name!r} has no values")
            names.append(str(name))
            parts.append(str(len(values)) + " "
                         + " ".join(str(v) for v in values))
        if self._proto_info is None:
            self._proto_info = names
        elif names != self._proto_info:
            raise ValueError(
                f"slot order changed between samples: {self._proto_info} "
                f"-> {names}")
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(_DataGeneratorBase):
    """Values are raw strings; no numeric validation (reference
    MultiSlotStringDataGenerator — the fast path)."""

    def _format(self, sample):
        if not isinstance(sample, (list, tuple)) or not sample:
            raise ValueError(
                "generate_sample must yield a non-empty list of "
                "(slot_name, values) pairs")
        parts = []
        for _, values in sample:
            parts.append(str(len(values)) + " "
                         + " ".join(str(v) for v in values))
        return " ".join(parts) + "\n"
