"""Activation recomputation (checkpointing).

Reference: `python/paddle/distributed/fleet/recompute/recompute.py:128`
(RecomputeFunction PyLayer: drop activations in forward, replay forward with
saved RNG state in backward) and the user API at `:463`.

TPU-native: two paths share this API —
- eager: a PyLayer that re-runs the function under the tape in backward
  (RNG states restored via the mpu tracker), same as the reference;
- compiled: `paddle_tpu.jit` functionalization maps recompute-wrapped calls
  to `jax.checkpoint` (XLA rematerialization), the idiomatic TPU form.
"""

from __future__ import annotations

from paddle_tpu.autograd import PyLayer
from paddle_tpu.core import tensor as _tmod
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core.backward import run_backward
from paddle_tpu.framework import random as _random

__all__ = ["recompute", "RecomputeFunction", "recompute_sequential"]


class RecomputeFunction(PyLayer):
    _force_record = True  # params enter via closure, not tensor args

    @staticmethod
    def forward(ctx, run_function, preserve_rng_state, *args):
        ctx.run_function = run_function
        ctx.preserve_rng_state = preserve_rng_state
        ctx.inputs = args
        if preserve_rng_state:
            ctx.fw_rng_state = _random.get_rng_state()
            from paddle_tpu.distributed.fleet.layers.mpu.random import (
                get_rng_state_tracker,
            )

            ctx.fw_tracker_states = get_rng_state_tracker().get_states_tracker()
        outputs = run_function(*args)
        return outputs

    @staticmethod
    def backward(ctx, *grads):
        # replay forward with grad enabled under the saved RNG state
        detached = []
        for a in ctx.inputs:
            if isinstance(a, Tensor):
                d = Tensor(a._data, stop_gradient=a.stop_gradient)
                detached.append(d)
            else:
                detached.append(a)

        rng_ctx = None
        if ctx.preserve_rng_state:
            cur = _random.get_rng_state()
            _random.set_rng_state(ctx.fw_rng_state)
            from paddle_tpu.distributed.fleet.layers.mpu.random import (
                get_rng_state_tracker,
            )

            tracker = get_rng_state_tracker()
            cur_tracker = tracker.get_states_tracker()
            tracker.set_states_tracker(ctx.fw_tracker_states)

        prev = _tmod.is_grad_enabled()
        _tmod.set_grad_enabled(True)
        try:
            outputs = ctx.run_function(*detached)
        finally:
            _tmod.set_grad_enabled(prev)
            if ctx.preserve_rng_state:
                _random.set_rng_state(cur)
                tracker.set_states_tracker(cur_tracker)

        outs = list(outputs) if isinstance(outputs, (tuple, list)) else [outputs]
        grads = list(grads)
        # backprop through the replayed subgraph
        seeds, gseeds = [], []
        for o, g in zip(outs, grads):
            if isinstance(o, Tensor) and not o.stop_gradient:
                seeds.append(o)
                gseeds.append(g)
        tensor_inputs = [d for d in detached if isinstance(d, Tensor)]
        for t in tensor_inputs:
            t.grad = None
        run_backward(seeds, gseeds, retain_graph=False)
        # one grad slot per Tensor input (PyLayer zips node.inputs <-> grads)
        return tuple(t.grad if t.grad is not None else None
                     for t in tensor_inputs) or (None,)


def recompute(function, *args, **kwargs):
    """Reference recompute.py:463. kwargs: use_reentrant, preserve_rng_state."""
    preserve = kwargs.pop("preserve_rng_state", True)
    kwargs.pop("use_reentrant", True)
    if kwargs:
        raise ValueError(f"unexpected kwargs {list(kwargs)}")
    if not _tmod.is_grad_enabled():
        return function(*args)
    # PyLayer.apply routes only Tensor args into autograd; run_function and
    # flags ride along as non-tensor args.
    return RecomputeFunction.apply(function, preserve, *args)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Reference incubate recompute_sequential: chunk a Sequential."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    funcs = list(functions)
    chunk = max(1, len(funcs) // max(1, segments))
    out = args
    for i in range(0, len(funcs), chunk):
        seg = funcs[i:i + chunk]

        def run_seg(*xs, _seg=seg):
            y = xs
            for f in _seg:
                y = f(*y) if isinstance(y, tuple) else f(y)
            return y

        out = recompute(run_seg, *(out if isinstance(out, tuple) else (out,)), **kwargs)
    return out
