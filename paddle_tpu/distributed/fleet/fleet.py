"""The fleet singleton: init / distributed_model / distributed_optimizer.

Reference: `python/paddle/distributed/fleet/fleet.py:218` (init: RoleMaker ->
init_parallel_env -> HybridCommunicateGroup) and `:1448`
(distributed_optimizer); model dispatch `fleet/model.py:33,143-188`.
"""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.base.distributed_strategy import DistributedStrategy
from paddle_tpu.distributed.fleet.base.topology import (
    CommunicateTopology, HybridCommunicateGroup,
)

__all__ = ["Fleet", "fleet"]

_ORDER_TO_TOPO = {"dp": "data", "pp": "pipe", "sharding": "sharding",
                  "sep": "sep", "mp": "model"}


class Fleet:
    def __init__(self):
        self._is_initialized = False
        self._hcg = None
        self._strategy = None
        self._user_defined_strategy = None

    def init(self, role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
        from paddle_tpu.distributed.parallel import init_parallel_env

        init_parallel_env()
        if strategy is None:
            strategy = DistributedStrategy()
        self._strategy = self._user_defined_strategy = strategy

        h = strategy.hybrid_configs
        import jax

        n = jax.device_count()
        degrees = {"dp": h["dp_degree"], "mp": h["mp_degree"],
                   "pp": h["pp_degree"], "sharding": h["sharding_degree"],
                   "sep": h["sep_degree"]}
        # infer a single unset degree (reference allows dp_degree=-1)
        known = 1
        unset = None
        for k, v in degrees.items():
            if v in (-1, None):
                unset = k
            else:
                known *= v
        if unset is not None:
            degrees[unset] = max(1, n // known)
        order = h.get("order") or ["dp", "pp", "sharding", "sep", "mp"]
        topo = CommunicateTopology(
            hybrid_group_names=[_ORDER_TO_TOPO[o] for o in order],
            dims=[degrees[o] for o in order])
        self._hcg = HybridCommunicateGroup(topo)
        self._is_initialized = True
        return self

    # -- accessors (reference fleet.py) -------------------------------------
    def is_first_worker(self):
        return self.worker_index() == 0

    def worker_index(self):
        from paddle_tpu.distributed.parallel import get_rank

        return get_rank()

    def worker_num(self):
        from paddle_tpu.distributed.parallel import get_world_size

        return get_world_size()

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def worker_endpoints(self):
        return [""]

    def barrier_worker(self):
        from paddle_tpu.distributed.communication import barrier

        barrier()

    # -- model / optimizer wrapping -----------------------------------------
    def distributed_model(self, model):
        """Reference fleet/model.py:143-188 dispatch by parallel mode."""
        if self._hcg is None:
            raise RuntimeError("call fleet.init() first")
        from paddle_tpu.distributed.fleet import meta_parallel as mp

        mode = self._hcg.get_parallel_mode()
        if mode == "data_parallel" :
            from paddle_tpu.distributed.parallel import DataParallel

            # dp axis mesh slice == full mesh when pure DP
            return DataParallel(model, mesh=None)
        if mode == "sharding_parallel":
            return mp.ShardingParallel(model, self._hcg, self._strategy)
        if mode == "segment_parallel":
            return mp.SegmentParallel(model, self._hcg, self._strategy)
        if mode == "pipeline_parallel":
            if isinstance(model, mp.PipelineLayer):
                return mp.PipelineParallel(model, self._hcg, self._strategy)
            raise TypeError(
                "pipeline parallel requires the model to be a PipelineLayer")
        if mode == "tensor_parallel":
            return mp.TensorParallel(model, self._hcg, self._strategy)
        return model

    def distributed_engine(self, model, loss=None, optimizer=None, **kwargs):
        """The compiled path behind distributed_model: build the generic
        one-jit `Engine` (reference auto-parallel `Engine`, engine.py:99)
        from this fleet's strategy — dp/sharding degrees become mesh axes
        and ZeRO sharding rules."""
        if self._hcg is None:
            raise RuntimeError("call fleet.init() first")
        from paddle_tpu.distributed.engine import Engine

        return Engine(model, loss=loss, optimizer=optimizer,
                      strategy=self._strategy, **kwargs)

    def distributed_optimizer(self, optimizer, strategy=None):
        """Reference fleet.py:1448 -> HybridParallelOptimizer."""
        if strategy is not None:
            self._strategy = strategy
        from paddle_tpu.distributed.fleet.meta_optimizers.dygraph_optimizer import (
            HybridParallelOptimizer,
        )

        if self._hcg is not None:
            return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)
        return optimizer


fleet = Fleet()
