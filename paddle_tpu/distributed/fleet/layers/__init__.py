"""fleet.layers: parallel layer library (reference fleet/layers/)."""

from paddle_tpu.distributed.fleet.layers import mpu  # noqa: F401
