"""TP communication primitives.

Reference: `python/paddle/distributed/fleet/layers/mpu/mp_ops.py` —
`_c_identity` (:77, identity fwd / allreduce grad), `_c_concat` (:122),
`_mp_allreduce` (:259, allreduce fwd / identity grad), `_c_split`,
`_c_softmax_with_cross_entropy` (:385).

TPU-native: under single-controller SPMD an eager value is global, so the
forward allreduce of a partial product is fused into the producing matmul by
XLA, and the backward identity/allreduce pair is what jax.vjp produces
naturally for sharded operands. These functions therefore reduce to sharding
annotations (`with_sharding_constraint`) that pin *where* the collective
happens when the step is jitted — the semantic content of the reference ops —
plus real `lax` collectives when called inside shard_map.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.tensor import Tensor, apply

__all__ = ["_c_identity", "_c_concat", "_c_split", "_mp_allreduce",
           "_parallel_linear", "_c_lookup_table",
           "_c_softmax_with_cross_entropy", "sharding_constraint"]


def _is_tracing(x):
    data = x._data if isinstance(x, Tensor) else x
    return isinstance(data, jax.core.Tracer)


def sharding_constraint(t, mesh, placements):
    """Pin a Tensor's sharding inside a jitted region (GSPMD hint)."""
    sharding = mesh.sharding(placements, t.ndim)
    return apply(lambda d: lax.with_sharding_constraint(d, sharding), t,
                 _name="sharding_constraint")


def _c_identity(tensor, group=None, skip_c_identity_dynamic=False):
    """Identity fwd; grad all-reduced over the mp group (mp_ops.py:77).

    Under GSPMD the grad psum is inserted automatically for operands
    replicated over 'mp'; eager single-controller grads are already global.
    """
    return tensor


def _mp_allreduce(tensor, op=None, group=None, use_calc_stream=True,
                  use_model_parallel=True):
    """Allreduce fwd; identity grad (mp_ops.py:259).

    Eager: a partial sum never escapes an op (XLA fuses the reduction), so
    this is identity. In shard_map traces it is a real psum.
    """
    if _is_tracing(tensor) and group is not None and group.axis_name:
        data = lax.psum(tensor._data if isinstance(tensor, Tensor) else tensor,
                        group.axis_name)
        return Tensor(data, stop_gradient=getattr(tensor, "stop_gradient", True)) \
            if isinstance(tensor, Tensor) else data
    return tensor


def _c_split(tensor, group=None):
    """Split along the last dim, keep this rank's chunk (mp_ops.py).

    Single-controller: re-sharding the last dim over 'mp'."""
    if group is None or group.mesh is None:
        return tensor
    from paddle_tpu.distributed.api import shard_tensor
    from paddle_tpu.distributed.placement import Replicate, Shard

    mesh = group.mesh
    placements = [Replicate()] * mesh.ndim
    placements[mesh.dim_names.index(group.axis_name)] = Shard(tensor.ndim - 1)
    return shard_tensor(tensor, mesh, placements,
                        stop_gradient=tensor.stop_gradient)


def _c_concat(tensor, group=None):
    """Gather chunks along the last dim (mp_ops.py:122): reshard to
    replicated over the mp axis."""
    if group is None or group.mesh is None:
        return tensor
    from paddle_tpu.distributed.api import shard_tensor
    from paddle_tpu.distributed.placement import Replicate

    mesh = group.mesh
    return shard_tensor(tensor, mesh, [Replicate()] * mesh.ndim,
                        stop_gradient=tensor.stop_gradient)


def _c_lookup_table(table, index, start_index=0, vocab_size=-1, name=None):
    """Vocab-parallel lookup (mp_ops.py:310): masked local lookup + psum.

    GSPMD handles a gather from a vocab-sharded table directly; this helper
    exists for API parity and for explicit shard_map kernels."""
    from paddle_tpu.nn import functional as F

    return F.embedding(index, table)


def _parallel_linear(x, weight, bias, transpose_weight=False, name=None):
    from paddle_tpu.ops.linalg import matmul

    out = matmul(x, weight, transpose_y=transpose_weight)
    if bias is not None:
        out = out + bias
    return out


def _c_softmax_with_cross_entropy(logits, label, group=None,
                                  return_softmax=False, ignore_index=-100):
    """Parallel CE over class-sharded logits (mp_ops.py:385).

    The reference computes local max/sum + two allreduces. GSPMD derives the
    same schedule from a class-dim-sharded logits array; we just compute the
    stable CE globally.
    """
    from paddle_tpu.nn.functional.loss import softmax_with_cross_entropy

    return softmax_with_cross_entropy(
        logits, label, return_softmax=return_softmax,
        ignore_index=ignore_index)
