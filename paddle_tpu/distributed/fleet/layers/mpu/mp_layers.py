"""Tensor-parallel layers: VocabParallelEmbedding / ColumnParallelLinear /
RowParallelLinear / ParallelCrossEntropy.

Reference: `python/paddle/distributed/fleet/layers/mpu/mp_layers.py` —
VocabParallelEmbedding (:49), ColumnParallelLinear (:336),
RowParallelLinear (:543), ParallelCrossEntropy (:744).

TPU-native: the reference allocates a *local* weight slice per rank and
issues explicit collectives. Here each layer allocates the *logical* weight
and shards it over the fleet mesh's 'mp' axis with a NamedSharding —
Column: weight[in, out] Shard on out; Row: weight[in, out] Shard on in;
Vocab embedding: table[vocab, hidden] Shard on vocab. Forward is the plain
dense op; XLA partitions it and inserts exactly the collectives the
reference hand-writes (psum for Row, grad-psum for Column). This keeps the
MXU tiles large and lets XLA fuse/overlap — the point of building TPU-first.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.distributed.api import shard_tensor
from paddle_tpu.distributed.placement import Replicate, Shard

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_context():
    """(mesh, mp_axis_index, mp_degree) from fleet; (None, -1, 1) outside."""
    from paddle_tpu.distributed import fleet

    hcg = fleet.get_hybrid_communicate_group()
    if hcg is None:
        return None, -1, 1
    mesh = hcg.mesh
    return mesh, mesh.dim_names.index("mp"), hcg.get_model_parallel_world_size()


def _shard_param(param, tensor_dim):
    """Shard `param` over the 'mp' mesh axis along `tensor_dim`."""
    mesh, mp_idx, degree = _mp_context()
    if mesh is None or degree == 1:
        return
    placements = [Replicate()] * mesh.ndim
    if param.shape[tensor_dim] % degree == 0:
        placements[mp_idx] = Shard(tensor_dim)
    param._data = shard_tensor(param, mesh, placements)._data


class VocabParallelEmbedding(nn.Layer):
    """Reference mp_layers.py:49: vocab-dim-sharded embedding table."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        _shard_param(self.weight, 0)

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(nn.Layer):
    """Reference mp_layers.py:336: weight sharded on the output dim.

    gather_output=True reshards the activation back to replicated (the
    reference's _c_concat); False leaves it mp-sharded on the last dim for a
    following RowParallelLinear — under GSPMD that is just *not* adding a
    constraint.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        self.bias = self.create_parameter(
            [out_features], attr=None, is_bias=True) if has_bias else None
        _shard_param(self.weight, 1)
        if self.bias is not None:
            _shard_param(self.bias, 0)

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            mesh, mp_idx, degree = _mp_context()
            if mesh is not None and degree > 1:
                from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import _c_concat
                from paddle_tpu.distributed import fleet

                out = _c_concat(
                    out, fleet.get_hybrid_communicate_group().get_model_parallel_group())
        return out


class RowParallelLinear(nn.Layer):
    """Reference mp_layers.py:543: weight sharded on the input dim; the
    output psum is inserted by XLA at the sharded contraction."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        self.bias = self.create_parameter(
            [out_features], attr=None, is_bias=True) if has_bias else None
        _shard_param(self.weight, 0)

    def forward(self, x):
        if not self.input_is_parallel:
            mesh, mp_idx, degree = _mp_context()
            if mesh is not None and degree > 1:
                from paddle_tpu.distributed import fleet
                from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import _c_split

                x = _c_split(
                    x, fleet.get_hybrid_communicate_group().get_model_parallel_group())
        return F.linear(x, self.weight, self.bias)


class ParallelCrossEntropy(nn.Layer):
    """Reference mp_layers.py:744 over class-dim-sharded logits."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import (
            _c_softmax_with_cross_entropy,
        )

        return _c_softmax_with_cross_entropy(
            input, label, ignore_index=self.ignore_index)
