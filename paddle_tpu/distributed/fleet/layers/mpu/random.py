"""Model-parallel RNG state tracker.

Reference: `python/paddle/distributed/fleet/layers/mpu/random.py` —
`RNGStatesTracker` keeps named RNG states so dropout inside TP regions uses a
*different* seed per mp rank ('local_seed') while replicated regions use the
same seed ('global_seed'); `model_parallel_random_seed` derives both.

TPU-native: RNG is counter-based (threefry keys). A "state" is a key; the
tracker swaps the framework's global key. Under single-controller SPMD a
dropout over an mp-sharded activation automatically draws independent bits
per shard (the key is split over positions), so local/global both map to
plain keys — kept distinct for checkpoint-format parity and for shard_map
kernels that fold in the axis index.
"""

from __future__ import annotations

import contextlib

from paddle_tpu.framework import random as _random

__all__ = ["RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed", "determinate_seed"]

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        cur = _random.get_rng_state()
        _random.seed(seed)
        self.states_[name] = _random.get_rng_state()
        _random.set_rng_state(cur)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        orig = _random.get_rng_state()
        _random.set_rng_state(self.states_[name])
        try:
            yield
        finally:
            self.states_[name] = _random.get_rng_state()
            _random.set_rng_state(orig)


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    """Derive global/local seeds from the mp rank (reference random.py)."""
    from paddle_tpu.distributed import fleet

    hcg = fleet.get_hybrid_communicate_group()
    rank = hcg.get_model_parallel_rank() if hcg else 0
    if seed:
        global_seed = seed
        local_seed = seed * 1024 + rank * 100
    else:
        global_seed = 100
        local_seed = 2048 + rank * 100
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
    _random.seed(global_seed)


def determinate_seed(rng_name):
    return 0
