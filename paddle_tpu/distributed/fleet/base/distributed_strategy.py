"""DistributedStrategy: the fleet config object.

Reference: `python/paddle/distributed/fleet/base/distributed_strategy.py:284`
wrapping protobuf `distributed_strategy.proto`; `hybrid_configs` at `:1892`,
`sharding_configs` at `:1570`.

TPU-native: plain attribute bag (no protobuf round-trip needed — the config
never crosses a process boundary under single-controller SPMD). Field names
and defaults mirror the reference so fleet scripts port unchanged.
"""

from __future__ import annotations

import copy

__all__ = ["DistributedStrategy"]

_HYBRID_DEFAULTS = {
    "dp_degree": -1,  # -1: infer from the device count (reference default)
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sep_degree": 1,
    "ep_degree": 1,
    "order": ["dp", "pp", "sharding", "sep", "mp"],
    "mp_configs": {},
    "pp_configs": {},
}

_SHARDING_DEFAULTS = {
    "sharding_degree": 8,
    "stage": 1,
    "offload": False,
    "segment_broadcast_MB": 32.0,
}

_PIPELINE_DEFAULTS = {
    "micro_batch_size": 1,
    "accumulate_steps": 1,
    "schedule_mode": "1F1B",
    "p2p_cache_shape": True,
}

_AMP_DEFAULTS = {
    "init_loss_scaling": 32768.0,
    "use_dynamic_loss_scaling": True,
    "custom_white_list": [],
    "custom_black_list": [],
    "use_pure_fp16": False,
    "use_bf16": True,
}

_RECOMPUTE_DEFAULTS = {"checkpoints": [], "enable_offload": False}


class DistributedStrategy:
    def __init__(self):
        self.amp = False
        self.recompute = False
        self.sharding = False
        self.pipeline = False
        self.gradient_merge = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.without_graph_optimization = True
        self._hybrid_configs = copy.deepcopy(_HYBRID_DEFAULTS)
        self._sharding_configs = copy.deepcopy(_SHARDING_DEFAULTS)
        self._pipeline_configs = copy.deepcopy(_PIPELINE_DEFAULTS)
        self._amp_configs = copy.deepcopy(_AMP_DEFAULTS)
        self._recompute_configs = copy.deepcopy(_RECOMPUTE_DEFAULTS)

    @property
    def hybrid_configs(self):
        return self._hybrid_configs

    @hybrid_configs.setter
    def hybrid_configs(self, configs):
        # reference checks unknown keys (distributed_strategy.py:1911)
        for k in configs:
            if k not in _HYBRID_DEFAULTS:
                raise ValueError(f"unknown hybrid config key {k!r}")
        self._hybrid_configs.update(configs)

    @property
    def sharding_configs(self):
        return self._sharding_configs

    @sharding_configs.setter
    def sharding_configs(self, configs):
        self._sharding_configs.update(configs)

    @property
    def pipeline_configs(self):
        return self._pipeline_configs

    @pipeline_configs.setter
    def pipeline_configs(self, configs):
        self._pipeline_configs.update(configs)

    @property
    def amp_configs(self):
        return self._amp_configs

    @amp_configs.setter
    def amp_configs(self, configs):
        self._amp_configs.update(configs)

    @property
    def recompute_configs(self):
        return self._recompute_configs

    @recompute_configs.setter
    def recompute_configs(self, configs):
        self._recompute_configs.update(configs)

    def __repr__(self):
        h = self._hybrid_configs
        return (f"DistributedStrategy(dp={h['dp_degree']}, mp={h['mp_degree']},"
                f" pp={h['pp_degree']}, sharding={h['sharding_degree']},"
                f" sep={h['sep_degree']})")
