"""fleet.base: strategy + topology."""
