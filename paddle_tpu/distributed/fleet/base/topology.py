"""Hybrid-parallel topology: CommunicateTopology + HybridCommunicateGroup.

Reference: `python/paddle/distributed/fleet/base/topology.py:189-229` — ranks
laid out row-major over the axis order, one communication group created per
axis per coordinate (`topology.py:212`).

TPU-native: the topology *is* a `ProcessMesh` whose dims are the parallel
axes. Instead of materializing O(prod(degrees)) NCCL communicators, each axis
becomes a mesh axis name; a Group along an axis is a description bound to
that name (collectives over it compile to ICI collectives via GSPMD or
shard_map). The rank→coordinate math is kept identical to the reference so
checkpoint/layer-placement logic ports over.
"""

from __future__ import annotations

import itertools

import numpy as np

import jax

from paddle_tpu.distributed.collective import new_group
from paddle_tpu.distributed.process_mesh import ProcessMesh, set_mesh

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep", "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = itertools.product(*(range(d) for d in dims))
        self._world = np.arange(int(np.prod(dims))).reshape(dims)
        self._coord_of = {}
        for coord, rank in np.ndenumerate(self._world):
            self._coord_of[int(rank)] = coord

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(self._world.size)

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return int(self._world[coord])

    def get_coord(self, rank):
        return self._coord_of[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on `axis_name` equals index."""
        axis = self._parallel_names.index(axis_name)
        sl = [slice(None)] * len(self._dims)
        sl[axis] = index
        return sorted(int(r) for r in self._world[tuple(sl)].flatten())

    def get_comm_list(self, axis_name):
        """List of rank-lists, one group per line along `axis_name`
        (reference topology.py get_comm_list)."""
        axis = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._world, axis, -1)
        return [list(map(int, line)) for line in moved.reshape(-1, self._dims[axis])]

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = dict(zip(self._parallel_names, self.get_coord(global_rank)))
        coord.update(kwargs)
        return self.get_rank(**coord)


class HybridCommunicateGroup:
    """Reference: topology.py:189 — builds dp/mp/pp/sharding/sep groups.

    TPU-native: also publishes `self.mesh`, a ProcessMesh with one dim per
    parallel axis (in topology order), which the compiled train step jits
    over. Axis naming: data->'dp', model->'mp', pipe->'pp',
    sharding->'sharding', sep->'sep'.
    """

    _AXIS_NAME = {"data": "dp", "model": "mp", "pipe": "pp",
                  "sharding": "sharding", "sep": "sep"}

    def __init__(self, topology):
        self._topo = topology
        from paddle_tpu.distributed.parallel import get_rank, init_parallel_env

        init_parallel_env()
        self.global_rank = get_rank()
        self.nranks = topology.world_size()

        self._dp_degree = topology.get_dim("data")
        self._mp_degree = topology.get_dim("model")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") if "sep" in topology.get_hybrid_group_names() else 1

        # the global device mesh: axes in topology order
        names = [self._AXIS_NAME[n] for n in topology.get_hybrid_group_names()]
        dims = [topology.get_dim(n) for n in topology.get_hybrid_group_names()]
        self.mesh = ProcessMesh(np.arange(int(np.prod(dims))).reshape(dims), names)
        set_mesh(self.mesh)

        coord = topology.get_coord(self.global_rank)
        self._coord = dict(zip(topology.get_hybrid_group_names(), coord))

        self._dp_group = self._make_group("data")
        self._mp_group = self._make_group("model")
        self._pp_group = self._make_group("pipe")
        self._sharding_group = self._make_group("sharding")
        self._sep_group = (self._make_group("sep")
                           if "sep" in topology.get_hybrid_group_names() else None)
        # pp peers: check group for send/recv pairing
        self._pp_comm_group = self._pp_group

    def _make_group(self, axis):
        idx_axes = {n: v for n, v in self._coord.items() if n != axis}
        ranks = [self._topo.get_rank(**{**idx_axes, axis: i})
                 for i in range(self._topo.get_dim(axis))]
        return new_group(ranks, axis_name=self._AXIS_NAME[axis], mesh=self.mesh)

    # -- degree / rank accessors (reference names) --------------------------
    def get_parallel_mode(self):
        # reference topology.py ParallelMode resolution order
        if self._mp_degree == 1 and self._pp_degree == 1 and \
                self._sharding_degree == 1 and self._sep_degree == 1:
            return "data_parallel" if self._dp_degree > 1 else "single"
        if self._sharding_degree > 1 and self._mp_degree == 1 and \
                self._pp_degree == 1:
            return "sharding_parallel"
        if self._sep_degree > 1 and self._mp_degree == 1 and self._pp_degree == 1:
            return "segment_parallel"
        if self._pp_degree > 1:
            return "pipeline_parallel"
        return "tensor_parallel"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._coord["data"]

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._coord["model"]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    # pipeline parallel
    def get_stage_id(self):
        return self._coord["pipe"]

    def get_pipe_parallel_rank(self):
        return self._coord["pipe"]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_p2p_groups(self):
        return None

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._coord["sharding"]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group.ranks[0]

    # sep
    def get_sep_parallel_rank(self):
        return self._coord.get("sep", 0)

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._sep_group

    # checks (reference: get_check_parallel_group)
    def get_check_parallel_group(self, sharding=False):
        return self._sharding_group if sharding else self._mp_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(
            self.global_rank, pipe=stage_id, **kwargs)
