"""fleet.UtilBase (reference `python/paddle/distributed/fleet/base/
util_factory.py`): small cross-worker utilities. The reference runs these
over Gloo; here they ride the TCPStore collective backend when
distributed is initialized, and degrade to single-process identities
otherwise (same contract as the reference under world_size==1)."""

from __future__ import annotations

__all__ = ["UtilBase"]


class UtilBase:
    def __init__(self):
        self.role_maker = None

    def _set_role_maker(self, role_maker):
        self.role_maker = role_maker

    @staticmethod
    def _world():
        import paddle_tpu.distributed as dist

        try:
            return dist.get_world_size()
        except Exception:
            return 1

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        if self._world() <= 1:
            return np.asarray(input)
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist

        t = paddle.to_tensor(np.asarray(input))
        op = {"sum": dist.ReduceOp.SUM, "max": dist.ReduceOp.MAX,
              "min": dist.ReduceOp.MIN}[mode]
        dist.all_reduce(t, op=op)
        return t.numpy()

    def all_gather(self, input, comm_world="worker"):
        if self._world() <= 1:
            return [input]
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist

        out = []
        dist.all_gather(out, paddle.to_tensor(np.asarray(input)))
        return [o.numpy() for o in out]

    def barrier(self, comm_world="worker"):
        if self._world() <= 1:
            return
        import paddle_tpu.distributed as dist

        dist.barrier()

    def get_file_shard(self, files):
        """Split a file list evenly over workers (reference
        UtilBase.get_file_shard): worker i takes files[i::n] style
        contiguous blocks, remainder to the first workers."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file names")
        rm = self.role_maker
        n = rm.worker_num() if rm is not None else 1
        idx = rm.worker_index() if rm is not None else 0
        per, rem = divmod(len(files), n)
        start = idx * per + min(idx, rem)
        return files[start:start + per + (1 if idx < rem else 0)]

    def print_on_rank(self, message, rank_id=0):
        rm = self.role_maker
        me = rm.worker_index() if rm is not None else 0
        if me == rank_id:
            print(message)
