"""Role makers (reference `python/paddle/distributed/fleet/base/
role_maker.py`): decide whether this process is a WORKER or a SERVER and
where its peers are. The reference reads MPI/Gloo or PaddleCloud env
vars; here the same env protocol is read directly (PADDLE_TRAINERS_NUM,
TRAINING_ROLE, PADDLE_PORT, POD_IP, PADDLE_PSERVERS_IP_PORT_LIST,
PADDLE_TRAINER_ID), and collective init happens over the TCPStore
backend instead of Gloo."""

from __future__ import annotations

import os

__all__ = ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """Resolve the role from PaddleCloud-style env vars (reference
    PaddleCloudRoleMaker._ps_env / _collective_env)."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
        self._kwargs = kwargs
        self._generated = False
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_num = 1
        self._server_endpoints = []
        self._worker_endpoints = []

    def _generate_role(self):
        if self._generated:
            return
        env = os.environ
        if self._is_collective:
            self._role = Role.WORKER
            self._current_id = int(env.get("PADDLE_TRAINER_ID", 0))
            eps = env.get("PADDLE_TRAINER_ENDPOINTS", "")
            self._worker_endpoints = eps.split(",") if eps else []
            self._worker_num = int(
                env.get("PADDLE_TRAINERS_NUM",
                        len(self._worker_endpoints) or 1))
        else:
            training_role = env.get("TRAINING_ROLE", "TRAINER")
            eps = env.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
            self._server_endpoints = eps.split(",") if eps else []
            self._worker_num = int(env.get("PADDLE_TRAINERS_NUM", 1))
            if training_role == "TRAINER":
                self._role = Role.WORKER
                self._current_id = int(env.get("PADDLE_TRAINER_ID", 0))
            elif training_role == "PSERVER":
                self._role = Role.SERVER
                me = (env.get("POD_IP", "127.0.0.1") + ":"
                      + env.get("PADDLE_PORT", "0"))
                self._current_id = (self._server_endpoints.index(me)
                                    if me in self._server_endpoints else 0)
            else:
                raise ValueError(
                    f"TRAINING_ROLE must be TRAINER or PSERVER, got "
                    f"{training_role!r}")
        self._generated = True

    def _is_worker(self):
        self._generate_role()
        return self._role == Role.WORKER

    is_worker = _is_worker

    def _is_server(self):
        self._generate_role()
        return self._role == Role.SERVER

    is_server = _is_server

    def _is_first_worker(self):
        return self._is_worker() and self._worker_index() == 0

    is_first_worker = _is_first_worker

    def _worker_index(self):
        self._generate_role()
        return self._current_id

    worker_index = _worker_index

    def _server_index(self):
        self._generate_role()
        return self._current_id

    server_index = _server_index

    def worker_num(self):
        self._generate_role()
        return self._worker_num

    def server_num(self):
        self._generate_role()
        return len(self._server_endpoints) or 0

    def get_pserver_endpoints(self):
        self._generate_role()
        return list(self._server_endpoints)

    def get_trainer_endpoints(self):
        self._generate_role()
        return list(self._worker_endpoints)


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Role passed explicitly instead of via env (reference
    UserDefinedRoleMaker)."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        super().__init__(is_collective=is_collective, **kwargs)
        self._role = kwargs.get("role", Role.WORKER)
        self._current_id = kwargs.get("current_id", 0)
        self._worker_num = kwargs.get("worker_num", 1)
        self._server_endpoints = list(kwargs.get("server_endpoints", []))
        self._worker_endpoints = list(kwargs.get("worker_endpoints", []))
        self._generated = True
