"""Hybrid-parallel optimizer wrappers.

Reference: `fleet/meta_optimizers/dygraph_optimizer/` —
HybridParallelOptimizer (grad clip across mp/pp groups),
HybridParallelGradScaler, DygraphShardingOptimizer (ZeRO stage-1: each rank
owns a param shard's optimizer states; fused reduce-scatter grad path in
`fleet/utils/tensor_fusion_helper.py:330,755`).

TPU-native: grads are globally exact under the single controller, so the
cross-group clip correction disappears; stage-1 sharding = placing optimizer
accumulators with Shard(0) over the 'sharding' mesh axis — XLA keeps the
update local to the owning shard and the reference's broadcast-back becomes
the (lazy) all-gather at next use.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.distributed.api import shard_tensor
from paddle_tpu.distributed.placement import Replicate, Shard

__all__ = ["HybridParallelOptimizer", "HybridParallelGradScaler",
           "DygraphShardingOptimizer"]


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero)

    def minimize(self, *a, **k):
        return self._inner_opt.minimize(*a, **k)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)


class HybridParallelGradScaler:
    def __init__(self, scaler, hcg):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, name):
        return getattr(self.__dict__["_scaler"], name)


class DygraphShardingOptimizer(HybridParallelOptimizer):
    """ZeRO stage-1 (reference dygraph_sharding_optimizer.py): optimizer
    states sharded over the 'sharding' axis."""

    def __init__(self, optimizer, hcg, strategy=None):
        super().__init__(optimizer, hcg, strategy)
        self._shard_states_installed = False

    def step(self):
        self._inner_opt.step()
        if not self._shard_states_installed:
            self._shard_accumulators()
            self._shard_states_installed = True

    def _shard_accumulators(self):
        mesh = self._hcg.mesh
        ax = mesh.dim_names.index("sharding")
        degree = self._hcg.get_sharding_parallel_world_size()
        if degree == 1:
            return
        accs = getattr(self._inner_opt, "_accumulators", None)
        if not accs:
            return
        import jax

        for key, acc in list(accs.items()):
            # accumulators are raw jnp arrays keyed by (slot_name, id(param))
            if hasattr(acc, "ndim") and acc.ndim >= 1 \
                    and acc.shape[0] % degree == 0:
                placements = [Replicate()] * mesh.ndim
                placements[ax] = Shard(0)
                sharding = mesh.sharding(placements, acc.ndim)
                accs[key] = jax.device_put(acc, sharding)
