"""fleet.meta_optimizers (reference fleet/meta_optimizers/)."""
