"""`paddle.distributed.fleet.auto` (reference
`python/paddle/distributed/fleet/__init__.py` re-export of auto_parallel):
the canonical spelling `from paddle.distributed.fleet import auto;
auto.Engine(...)`."""

from paddle_tpu.distributed.api import (  # noqa: F401
    dtensor_from_fn, reshard, shard_layer, shard_tensor,
)
from paddle_tpu.distributed.auto_parallel.static import Engine  # noqa: F401
from paddle_tpu.distributed.auto_parallel.strategy import Strategy  # noqa: F401

__all__ = ["Engine", "Strategy", "shard_tensor", "reshard", "shard_layer",
           "dtensor_from_fn"]
