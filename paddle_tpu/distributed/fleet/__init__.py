"""paddle.distributed.fleet — hybrid-parallel training API.

Reference: `python/paddle/distributed/fleet/` (`fleet.py:218` init).
Usage (identical to the reference):

    import paddle_tpu.distributed.fleet as fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(opt)
"""

from paddle_tpu.distributed.fleet.base.distributed_strategy import (  # noqa: F401
    DistributedStrategy,
)
from paddle_tpu.distributed.fleet.base.topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup,
)
from paddle_tpu.distributed.fleet.fleet import Fleet, fleet as _fleet_singleton  # noqa: F401
from paddle_tpu.distributed.fleet import meta_parallel  # noqa: F401
from paddle_tpu.distributed.fleet import recompute as _recompute_mod  # noqa: F401
from paddle_tpu.distributed.fleet.recompute import recompute  # noqa: F401

# module-level singleton dispatch (reference fleet/__init__.py)
init = _fleet_singleton.init
distributed_model = _fleet_singleton.distributed_model
distributed_engine = _fleet_singleton.distributed_engine
distributed_optimizer = _fleet_singleton.distributed_optimizer
worker_index = _fleet_singleton.worker_index
worker_num = _fleet_singleton.worker_num
is_first_worker = _fleet_singleton.is_first_worker
barrier_worker = _fleet_singleton.barrier_worker


def get_hybrid_communicate_group():
    return _fleet_singleton.get_hybrid_communicate_group()


def _reset_for_tests():
    """Reset singleton state (tests only)."""
    _fleet_singleton._is_initialized = False
    _fleet_singleton._hcg = None
    _fleet_singleton._strategy = None

# -- r5 final sweep: role makers + PS data generators (reference
#    python/paddle/distributed/fleet/base/role_maker.py and
#    .../data_generator/data_generator.py) -----------------------------------
from paddle_tpu.distributed.fleet.base.role_maker import (  # noqa: F401
    PaddleCloudRoleMaker, Role, UserDefinedRoleMaker,
)
from paddle_tpu.distributed.fleet.base.util_factory import UtilBase  # noqa: F401
from paddle_tpu.distributed.fleet.data_generator import (  # noqa: F401
    MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)
