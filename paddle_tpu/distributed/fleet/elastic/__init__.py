"""Elastic training: membership, fault tolerance, scale in/out.

Reference: `python/paddle/distributed/fleet/elastic/manager.py:125-251`
(ElasticManager over etcd leases/watches) + launch supervision
(`launch/controllers/watcher.py`).

TPU-native design: etcd is replaced by the framework's own TCPStore
(csrc/store.cc) — node membership is a set of lease keys each node
refreshes on a heartbeat thread; a lease whose payload stops advancing is
expired (same liveness rule as the comm monitor). The manager classifies
the world as HOLD (waiting for min_np), READY (within [min_np, max_np]),
SCALED (membership changed since the last sync — restart with the new
world), or FAILED (below min_np after the grace window). The
ElasticSupervisor (used by `launch --elastic`) restarts the local trainer
process on faults and on scale events, up to max_restarts — the watcher's
job in the reference.
"""

from __future__ import annotations

import enum
import os
import subprocess
import sys
import threading
import time

__all__ = ["ElasticStatus", "ElasticLevel", "ElasticManager",
           "ElasticSupervisor", "WorldSupervisor"]


class ElasticStatus(enum.Enum):
    HOLD = "hold"        # below min_np, inside the grace window
    READY = "ready"      # stable world within [min_np, max_np]
    SCALED = "scaled"    # membership changed since last sync -> restart
    FAILED = "failed"    # below min_np after the grace window
    COMPLETED = "completed"


class ElasticLevel:
    FAULT_TOLERANCE = 1  # fixed np, restart on failure (min == max)
    ELASTIC = 2          # np may move within [min, max]


def _parse_np(np_spec):
    """'2:4' -> (2, 4); '4' -> (4, 4) (reference _parse_np)."""
    if isinstance(np_spec, int):
        return np_spec, np_spec
    if ":" in str(np_spec):
        lo, hi = str(np_spec).split(":")
        return int(lo), int(hi)
    n = int(np_spec)
    return n, n


class ElasticManager:
    def __init__(self, store, node_id, np="1", ttl=3.0, grace=None,
                 job_id="default"):
        self.store = store
        self.node_id = str(node_id)
        self.min_np, self.max_np = _parse_np(np)
        self.level = (ElasticLevel.ELASTIC if self.max_np > self.min_np
                      else ElasticLevel.FAULT_TOLERANCE)
        self.ttl = ttl
        self.grace = grace if grace is not None else float(
            os.environ.get("PADDLE_ELASTIC_TIMEOUT", 30.0))
        self.prefix = f"elastic/{job_id}"
        self.enable = store is not None
        self._stop = threading.Event()
        self._known = {}      # node -> (payload, monotonic-last-change)
        self._synced = None   # membership at the last sync point
        self._below_since = None
        if self.enable:
            self._register()
            self._thread = threading.Thread(target=self._beat, daemon=True)
            self._thread.start()

    # -- membership ----------------------------------------------------------
    def _key(self, node):
        return f"{self.prefix}/nodes/{node}"

    def _register(self):
        self.store.set(self._key(self.node_id), repr(time.time()))
        # atomic membership registration: ADD allocates a slot index, the
        # slot key records the node id (no read-modify-write races)
        idx = self.store.add(f"{self.prefix}/nnodes", 1) - 1
        self.store.set(f"{self.prefix}/id/{idx}", self.node_id)

    def _known_ids(self):
        n = self.store.add(f"{self.prefix}/nnodes", 0)
        known = {self.node_id}
        for i in range(int(n)):
            v = self._try_get(f"{self.prefix}/id/{i}")
            if v is not None:
                known.add(v.decode())
        return known

    def _try_get(self, key):
        try:
            return self.store.get(key, timeout=0.5)
        except Exception:
            return None

    def _beat(self):
        while not self._stop.is_set():
            try:
                self.store.set(self._key(self.node_id), repr(time.time()))
            except Exception:
                pass
            self._stop.wait(self.ttl / 3.0)

    def alive_nodes(self):
        """Nodes whose lease payload advanced within the ttl window."""
        known = self._known_ids()
        now = time.monotonic()
        alive = []
        for node in sorted(known):
            val = self._try_get(self._key(node))
            if val is None:
                continue
            prev = self._known.get(node)
            if prev is None or prev[0] != val:
                self._known[node] = (val, now)
                alive.append(node)
            elif now - prev[1] <= max(self.ttl, 2.0):
                alive.append(node)
        return alive

    # -- status machine (reference manager.watch) ---------------------------
    def sync(self):
        """Mark the current membership as the running world."""
        self._synced = tuple(self.alive_nodes())
        self._below_since = None
        return self._synced

    def watch(self):
        alive = self.alive_nodes()
        n = len(alive)
        if n < self.min_np:
            if self._below_since is None:
                self._below_since = time.monotonic()
            if time.monotonic() - self._below_since > self.grace:
                return ElasticStatus.FAILED
            return ElasticStatus.HOLD
        self._below_since = None
        if self._synced is None:
            return ElasticStatus.READY
        if tuple(alive) != self._synced:
            # any membership change (join, leave, or replacement) means the
            # running world is stale: restart against the new one
            return ElasticStatus.SCALED
        return ElasticStatus.READY

    def exit(self, completed=True):
        self._stop.set()
        if self.enable:
            try:
                self.store.set(f"{self.prefix}/status/{self.node_id}",
                               "completed" if completed else "failed")
            except Exception:
                pass


class ElasticSupervisor:
    """Launch-side watcher (reference launch/controllers/watcher.py +
    elastic restart loop): run the trainer as a subprocess, restart it on
    failure or scale events up to max_restarts.

    `checkpoint_dir` turns restart into RESUME: the supervisor exports
    `PADDLE_CHECKPOINT_DIR` into every (re)spawned trainer, and a trainer
    that opens `CheckpointManager()` (no args) and calls `.resume(state)`
    picks up training from the newest committed snapshot instead of from
    step 0 — the restart loop and the checkpoint layer meet here.
    """

    def __init__(self, cmd, env=None, env_fn=None, max_restarts=3,
                 manager=None, poll_interval=0.5, log=print, log_dir=None,
                 rank=0, checkpoint_dir=None):
        self.cmd = cmd
        self.env = env
        # env_fn(manager) -> env dict, evaluated at EVERY (re)spawn so a
        # restart after scale-in/out sees the new world size, not the env
        # snapshot from job start
        self.env_fn = env_fn
        self.max_restarts = max_restarts
        self.manager = manager
        self.poll_interval = poll_interval
        self.restarts = 0
        self.log = log
        self.log_dir = log_dir
        self.rank = rank
        self.checkpoint_dir = checkpoint_dir

    def _spawn(self):
        env = self.env_fn(self.manager) if self.env_fn is not None else self.env
        if self.checkpoint_dir is not None:
            env = dict(os.environ if env is None else env)
            env["PADDLE_CHECKPOINT_DIR"] = self.checkpoint_dir
        if self.log_dir:
            # per-rank log files (reference launch/job/container.py): each
            # attempt appends, stdout+stderr interleaved
            os.makedirs(self.log_dir, exist_ok=True)
            logf = open(os.path.join(
                self.log_dir, f"rank_{self.rank}.log"), "ab")
            logf.write(f"\n===== attempt {self.restarts} =====\n".encode())
            logf.flush()
            return subprocess.Popen(self.cmd, env=env, stdout=logf,
                                    stderr=subprocess.STDOUT)
        return subprocess.Popen(self.cmd, env=env)

    def run(self):
        """Returns the final exit code."""
        while True:
            if self.manager is not None:
                self.manager.sync()
            proc = self._spawn()
            restart = False
            while True:
                rc = proc.poll()
                if rc is not None:
                    if rc == 0:
                        if self.manager is not None:
                            self.manager.exit(completed=True)
                        return 0
                    self.log(f"[elastic] trainer exited rc={rc}")
                    restart = True
                    break
                if self.manager is not None:
                    status = self.manager.watch()
                    if status == ElasticStatus.SCALED:
                        self.log("[elastic] membership changed -> restart "
                                 "with the new world")
                        proc.terminate()
                        try:
                            proc.wait(timeout=30)
                        except subprocess.TimeoutExpired:
                            proc.kill()
                        restart = True
                        break
                    if status == ElasticStatus.FAILED:
                        self.log("[elastic] world below min_np past grace "
                                 "-> abort")
                        proc.terminate()
                        if self.manager is not None:
                            self.manager.exit(completed=False)
                        return 1
                time.sleep(self.poll_interval)
            if not restart:
                return 1
            self.restarts += 1
            if self.restarts > self.max_restarts:
                self.log(f"[elastic] exceeded max_restarts="
                         f"{self.max_restarts}; giving up")
                if self.manager is not None:
                    self.manager.exit(completed=False)
                return 1
            self.log(f"[elastic] restart {self.restarts}/{self.max_restarts}")


def _free_port(host="127.0.0.1"):
    import socket

    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


class WorldSupervisor:
    """Whole-world fault-tolerant launcher: spawn every rank of a (single
    host) world, watch for ANY rank dying, kill the survivors, and restart
    the complete world against a fresh rendezvous — the
    detect -> kill survivors -> restart -> restore loop the reference's
    launch watcher + elastic manager implement across nodes.

    Detection is two-level and composes with `comm_monitor`: the
    supervisor sees the first dead rank's exit within `poll_interval`;
    meanwhile the SURVIVING ranks' heartbeat monitors declare the peer
    dead and raise `RankFailure` between steps, so they exit instead of
    hanging in a collective (and ranks stuck inside an XLA collective get
    SIGTERM'd here regardless — XLA collectives cannot be aborted).

    Restart is resume: `checkpoint_dir` is exported as
    `PADDLE_CHECKPOINT_DIR` into every spawned rank, so trainers using
    `CheckpointManager` (`HybridParallelEngine(save_every=..., resume=
    True)`) continue from the newest COMMITTED step.
    """

    def __init__(self, cmd_fn, nprocs, checkpoint_dir=None, max_restarts=3,
                 poll_interval=0.2, grace=10.0, log=print, log_dir=None,
                 master_host="127.0.0.1", env_fn=None, port_fn=None):
        # cmd_fn(rank, attempt) -> argv (a static argv list also works)
        self.cmd_fn = (cmd_fn if callable(cmd_fn)
                       else (lambda rank, attempt: list(cmd_fn)))
        self.nprocs = int(nprocs)
        self.checkpoint_dir = checkpoint_dir
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.grace = grace
        self.log = log
        self.log_dir = log_dir
        self.master_host = master_host
        # env_fn(rank, attempt) -> extra env; the chaos tests use it to arm
        # PADDLE_CHAOS on one rank of one attempt only
        self.env_fn = env_fn
        self.port_fn = port_fn or (lambda: _free_port(master_host))
        self.restarts = 0

    def _spawn_world(self, attempt):
        # a FRESH master port per attempt: the previous world's rendezvous
        # store (master port + 1) may linger in TIME_WAIT or still be held
        # by a survivor mid-SIGTERM
        port = self.port_fn()
        procs = []
        for rank in range(self.nprocs):
            env = dict(os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(self.nprocs),
                "PADDLE_MASTER": f"{self.master_host}:{port}",
                "PADDLE_RESTART_ATTEMPT": str(attempt),
            })
            if self.checkpoint_dir is not None:
                env["PADDLE_CHECKPOINT_DIR"] = self.checkpoint_dir
            if self.env_fn is not None:
                env.update(self.env_fn(rank, attempt) or {})
            stdout = stderr = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                logf = open(os.path.join(self.log_dir,
                                         f"rank_{rank}.log"), "ab")
                logf.write(f"\n===== attempt {attempt} =====\n".encode())
                logf.flush()
                stdout, stderr = logf, subprocess.STDOUT
            procs.append(subprocess.Popen(
                self.cmd_fn(rank, attempt), env=env,
                stdout=stdout, stderr=stderr))
        return procs

    def _kill_survivors(self, procs):
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + self.grace
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()  # stuck inside an XLA collective; no cleanup
                    p.wait()

    def _watch(self, procs):
        """0 once every rank exited 0; on the first nonzero/signalled exit,
        kill the survivors and return that rank's code."""
        while True:
            codes = [p.poll() for p in procs]
            for rank, rc in enumerate(codes):
                if rc is not None and rc != 0:
                    self.log(f"[world-supervisor] rank {rank} died rc={rc} "
                             "-> killing survivors, restarting the world")
                    self._kill_survivors(procs)
                    return rc
            if all(rc == 0 for rc in codes):
                return 0
            time.sleep(self.poll_interval)

    def run(self):
        """Final exit code: 0 when a (re)started world ran to completion."""
        attempt = 0
        while True:
            procs = self._spawn_world(attempt)
            rc = self._watch(procs)
            if rc == 0:
                if attempt:
                    self.log(f"[world-supervisor] world completed after "
                             f"{attempt} restart(s)")
                return 0
            self.restarts += 1
            if self.restarts > self.max_restarts:
                self.log(f"[world-supervisor] exceeded max_restarts="
                         f"{self.max_restarts}; giving up")
                return rc
            attempt += 1
            self.log(f"[world-supervisor] restart {self.restarts}/"
                     f"{self.max_restarts}")
