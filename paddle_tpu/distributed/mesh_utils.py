"""Hybrid DCN x ICI device meshes for multi-host / multi-slice training.

The reference scales across hosts by running NCCL over NVLink inside a
node and over IB/ethernet between nodes, with fleet's topology assigning
dp to the slow wires (`fleet/base/topology.py:189`). The TPU equivalent:
a pod SLICE is the fast ICI domain; slices connect over DCN. The standard
layout (scaling-book recipe) is therefore

    dp      -> DCN (gradient all-reduce once a step tolerates latency)
    mp/pp/..-> ICI (per-layer collectives need bandwidth)

`create_hybrid_mesh` builds exactly that: the outermost axis spans
slices, every other axis stays inside a slice, delegating to
`jax.experimental.mesh_utils.create_hybrid_device_mesh` when the runtime
exposes multiple slices and degrading to the plain (single-slice) mesh
builder otherwise — so the same training script runs unchanged from one
chip to a multi-slice pod. Feed the result to `HybridParallelEngine`
(`devices=`) or any `shard_map`/`pjit` program.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["create_hybrid_mesh", "single_axis_mesh", "slice_count",
           "shard_map_compat"]


_legacy_rules_registered = False


def _register_legacy_rep_rules():
    """Teach the legacy replication checker the identity primitives our
    programs use (checkpoint_name lacks a rule there). Best-effort: private
    registry, so failures just leave the checker stricter."""
    global _legacy_rules_registered
    if _legacy_rules_registered:
        return
    _legacy_rules_registered = True
    try:
        from jax._src.ad_checkpoint import name_p
        from jax.experimental import shard_map as smod

        smod.register_standard_check(name_p)
        smod.register_standard_rewrite(name_p)
    except Exception:
        pass


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=True):
    """`jax.shard_map` across jax versions: the top-level alias (and its
    `check_vma` spelling) only exist on newer jax; older versions carry
    `jax.experimental.shard_map.shard_map` with the pre-vma `check_rep`
    checker. The checker stays ON there: where legacy cannot analyze a
    program (e.g. lax.cond branches) it fails LOUDLY with a clear message
    — strictly better than check_rep=False, under which AD'd paths that
    rely on vma-typed transposes produce silently wrong gradients."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy_sm

    _register_legacy_rep_rules()
    return legacy_sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=check_vma)


def axis_size_compat(axis_name):
    """Static mesh-axis size inside shard_map across jax versions:
    `jax.lax.axis_size` on newer jax; the axis-env frame (which already
    carries the static size) on legacy."""
    ax = getattr(jax.lax, "axis_size", None)
    if ax is not None:
        return ax(axis_name)
    from jax.core import axis_frame

    frame = axis_frame(axis_name)
    if isinstance(frame, int):  # 0.4.x returns the size directly
        return frame
    return frame.size  # raise HERE if neither shape fits, not at range(P)


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _legacy_pvary(x, axes):
    return x


def _legacy_pvary_fwd(x, axes):
    return x, None


def _legacy_pvary_bwd(axes, _, g):
    return (jax.lax.psum(g, axes),)


_legacy_pvary.defvjp(_legacy_pvary_fwd, _legacy_pvary_bwd)


def pcast_compat(x, axes, to="varying"):
    """`jax.lax.pcast` when it exists (the vma cast newer shard_map needs).
    On legacy jax the cast is identity in forward, but its AD transpose is
    load-bearing: replicated->varying casts psum the cotangent over `axes`
    (how replicated params' grads get combined across e.g. 'pp'). Emulated
    with a custom_vjp so the AD'd schedule paths stay numerically correct
    under the legacy shard_map."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axes, to=to)
    if to != "varying":
        raise NotImplementedError(
            f"pcast_compat only emulates to='varying' on legacy jax, "
            f"got to={to!r}")
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    if not axes:
        return x
    return _legacy_pvary(x, axes)


def slice_count(devices=None):
    """Number of DCN-connected slices among `devices` (1 on single-slice
    or CPU platforms, whose devices carry no slice_index)."""
    devices = list(devices if devices is not None else jax.devices())
    return len({getattr(d, "slice_index", 0) for d in devices})


def single_axis_mesh(axis, degree, devices=None):
    """A one-axis Mesh over the first `degree` devices — the
    tensor-parallel serving mesh (`serving.PagedEngine(mesh=...)`), and
    the degenerate case of `create_hybrid_mesh` that doesn't require the
    axes product to cover every device on the host."""
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < degree:
        raise ValueError(
            f"axis {axis!r} needs {degree} devices, got {len(devices)}")
    return create_hybrid_mesh({axis: int(degree)}, devices[:int(degree)])


def create_hybrid_mesh(axes, devices=None, dcn_axis=None):
    """Build a Mesh whose `dcn_axis` (default: the first axis with degree
    > 1) spans slices over DCN and whose remaining axes stay inside a
    slice on ICI.

    axes: dict name -> degree, e.g. {"dp": 2, "pp": 2, "mp": 2}. The
    product must equal the device count. Returns jax.sharding.Mesh with
    the axes in the given order.

    On a single slice (or CPU) this is the ordinary row-major mesh — the
    function is safe to call unconditionally."""
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes)
    degrees = [int(axes[n]) for n in names]
    total = int(np.prod(degrees))
    if total != len(devices):
        raise ValueError(
            f"axes {axes} need {total} devices, got {len(devices)}")
    if dcn_axis is not None and dcn_axis not in axes:
        # validate regardless of slice count: a typo here would otherwise
        # only surface as a KeyError on the real multi-slice pod
        raise ValueError(f"dcn_axis {dcn_axis!r} is not one of {names}")

    n_slices = slice_count(devices)
    if n_slices > 1:
        from jax.experimental import mesh_utils

        dcn_name = dcn_axis or next(
            (n for n, d in zip(names, degrees) if d > 1), names[0])
        if axes[dcn_name] % n_slices != 0:
            raise ValueError(
                f"DCN axis {dcn_name!r} degree {axes[dcn_name]} must be "
                f"divisible by the slice count {n_slices}")
        # the dcn axis splits as (n_slices over DCN) x (remainder on ICI);
        # every other axis lives wholly inside a slice
        ici_parallelism = [axes[n] // n_slices if n == dcn_name else axes[n]
                           for n in names]
        dcn_parallelism = [n_slices if n == dcn_name else 1 for n in names]
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_parallelism, dcn_parallelism, devices=devices)
        return Mesh(dev_array, names)

    dev_array = np.asarray(devices).reshape(degrees)
    return Mesh(dev_array, names)
