"""Hybrid DCN x ICI device meshes for multi-host / multi-slice training.

The reference scales across hosts by running NCCL over NVLink inside a
node and over IB/ethernet between nodes, with fleet's topology assigning
dp to the slow wires (`fleet/base/topology.py:189`). The TPU equivalent:
a pod SLICE is the fast ICI domain; slices connect over DCN. The standard
layout (scaling-book recipe) is therefore

    dp      -> DCN (gradient all-reduce once a step tolerates latency)
    mp/pp/..-> ICI (per-layer collectives need bandwidth)

`create_hybrid_mesh` builds exactly that: the outermost axis spans
slices, every other axis stays inside a slice, delegating to
`jax.experimental.mesh_utils.create_hybrid_device_mesh` when the runtime
exposes multiple slices and degrading to the plain (single-slice) mesh
builder otherwise — so the same training script runs unchanged from one
chip to a multi-slice pod. Feed the result to `HybridParallelEngine`
(`devices=`) or any `shard_map`/`pjit` program.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["create_hybrid_mesh", "slice_count"]


def slice_count(devices=None):
    """Number of DCN-connected slices among `devices` (1 on single-slice
    or CPU platforms, whose devices carry no slice_index)."""
    devices = list(devices if devices is not None else jax.devices())
    return len({getattr(d, "slice_index", 0) for d in devices})


def create_hybrid_mesh(axes, devices=None, dcn_axis=None):
    """Build a Mesh whose `dcn_axis` (default: the first axis with degree
    > 1) spans slices over DCN and whose remaining axes stay inside a
    slice on ICI.

    axes: dict name -> degree, e.g. {"dp": 2, "pp": 2, "mp": 2}. The
    product must equal the device count. Returns jax.sharding.Mesh with
    the axes in the given order.

    On a single slice (or CPU) this is the ordinary row-major mesh — the
    function is safe to call unconditionally."""
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes)
    degrees = [int(axes[n]) for n in names]
    total = int(np.prod(degrees))
    if total != len(devices):
        raise ValueError(
            f"axes {axes} need {total} devices, got {len(devices)}")
    if dcn_axis is not None and dcn_axis not in axes:
        # validate regardless of slice count: a typo here would otherwise
        # only surface as a KeyError on the real multi-slice pod
        raise ValueError(f"dcn_axis {dcn_axis!r} is not one of {names}")

    n_slices = slice_count(devices)
    if n_slices > 1:
        from jax.experimental import mesh_utils

        dcn_name = dcn_axis or next(
            (n for n, d in zip(names, degrees) if d > 1), names[0])
        if axes[dcn_name] % n_slices != 0:
            raise ValueError(
                f"DCN axis {dcn_name!r} degree {axes[dcn_name]} must be "
                f"divisible by the slice count {n_slices}")
        # the dcn axis splits as (n_slices over DCN) x (remainder on ICI);
        # every other axis lives wholly inside a slice
        ici_parallelism = [axes[n] // n_slices if n == dcn_name else axes[n]
                           for n in names]
        dcn_parallelism = [n_slices if n == dcn_name else 1 for n in names]
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_parallelism, dcn_parallelism, devices=devices)
        return Mesh(dev_array, names)

    dev_array = np.asarray(devices).reshape(degrees)
    return Mesh(dev_array, names)
