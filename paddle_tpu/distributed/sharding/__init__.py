"""User API for group-sharded (ZeRO) training.

Reference: `python/paddle/distributed/sharding/group_sharded.py:50` —
`group_sharded_parallel(model, optimizer, level='os'|'os_g'|'p_g_os', ...)`
and `save_group_sharded_model`.
"""

from __future__ import annotations

from paddle_tpu.distributed.fleet.meta_parallel.sharding import (
    GroupShardedOptimizerStage2, GroupShardedStage2, GroupShardedStage3,
)

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def _default_group():
    import jax
    import numpy as np

    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.collective import new_group
    from paddle_tpu.distributed.process_mesh import ProcessMesh

    hcg = fleet.get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.get_sharding_parallel_group()
    n = jax.device_count()
    mesh = ProcessMesh(np.arange(n), ["sharding"])
    return new_group(list(range(n)), axis_name="sharding", mesh=mesh)


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """Reference group_sharded.py:50. level: 'os' (stage1), 'os_g' (stage2),
    'p_g_os' (stage3)."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError("level must be one of 'os', 'os_g', 'p_g_os'")
    group = group or _default_group()
    opt = GroupShardedOptimizerStage2(
        params=list(model.parameters()), optim=optimizer, group=group,
        offload=offload)
    if level == "os":
        return model, opt, scaler
    if level == "os_g":
        model = GroupShardedStage2(model, opt, group=group,
                                   sync_buffers=sync_buffers,
                                   buffer_max_size=buffer_max_size)
    else:
        model = GroupShardedStage3(model, optimizer=opt, group=group,
                                   sync_comm=sync_comm,
                                   segment_size=segment_size)
    return model, opt, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Reference group_sharded.py save_group_sharded_model."""
    import os

    import paddle_tpu as paddle

    if isinstance(model, GroupShardedStage3):
        model.get_all_parameters()
    layer = getattr(model, "_layers", model)
    os.makedirs(output, exist_ok=True)
    paddle.save(layer.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        paddle.save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
