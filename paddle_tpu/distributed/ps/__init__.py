"""Parameter-server training primitives (reference:
`paddle/fluid/distributed/ps/` service+table C++ stack and
`python/paddle/distributed/ps/` — sparse-recommendation training where
huge embedding tables live on server ranks and trainers pull/push rows).

TPU-native scope: the reference's brpc service + table zoo exists for
CPU-cluster recommendation models; on this stack the *protocol* is what
matters for capability parity. Tables are numpy-backed on the server
(sparse rows materialize on demand), transport is the framework's
`distributed.rpc` (TCPStore-rendezvoused TCP), and trainers embed pulled
rows into device computations. Dense training should use the collective
path (fleet/Engine) — this module is for the sparse pull/push pattern.
"""

from __future__ import annotations

import threading

import numpy as np

__all__ = ["SparseTable", "init_server", "shutdown_server", "pull_sparse",
           "push_sparse", "pull_dense", "push_dense", "get_table"]


class SparseTable:
    """Row-sharded embedding table with lazy row creation and SGD push
    (reference `ps/table/memory_sparse_table.cc` semantics)."""

    def __init__(self, dim, initializer="uniform", init_scale=0.01, lr=0.05,
                 seed=0):
        self.dim = dim
        self.lr = lr
        self.init_scale = init_scale
        self.initializer = initializer
        self._rows = {}
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()

    def _row(self, key):
        r = self._rows.get(int(key))
        if r is None:
            if self.initializer == "zeros":
                r = np.zeros(self.dim, np.float32)
            else:
                r = self._rng.uniform(-self.init_scale, self.init_scale,
                                      self.dim).astype(np.float32)
            self._rows[int(key)] = r
        return r

    def pull(self, ids):
        keys = np.asarray(ids).ravel()
        if keys.size == 0:  # empty feature batch: valid in sparse workloads
            return np.zeros((0, self.dim), np.float32)
        with self._lock:
            return np.stack([self._row(k) for k in keys])

    def push(self, ids, grads, lr=None):
        lr = lr if lr is not None else self.lr
        grads = np.asarray(grads, np.float32)
        with self._lock:
            for k, g in zip(np.asarray(ids).ravel(), grads):
                self._rows[int(k)] = self._row(k) - lr * g

    def size(self):
        return len(self._rows)


class DenseTable:
    def __init__(self, shape, lr=0.05, seed=0):
        self.value = np.random.RandomState(seed).uniform(
            -0.01, 0.01, shape).astype(np.float32)
        self.lr = lr
        self._lock = threading.Lock()

    def pull(self):
        with self._lock:
            return self.value.copy()

    def push(self, grad, lr=None):
        with self._lock:
            self.value -= (lr if lr is not None else self.lr) * np.asarray(
                grad, np.float32)


_tables = {}
_server_worker = None  # rpc worker name hosting the tables; None = local


# -- server-side functions (invoked via rpc on the server rank) -------------

def _srv_create(name, kind, **kwargs):
    _tables[name] = (SparseTable(**kwargs) if kind == "sparse"
                     else DenseTable(**kwargs))
    return True


def _srv_pull_sparse(name, ids):
    return _tables[name].pull(ids)


def _srv_push_sparse(name, ids, grads, lr=None):
    _tables[name].push(ids, grads, lr)
    return True


def _srv_pull_dense(name):
    return _tables[name].pull()


def _srv_push_dense(name, grad, lr=None):
    _tables[name].push(grad, lr)
    return True


def _srv_shutdown():
    _tables.clear()
    return True


def _call(fn, *args, **kwargs):
    if _server_worker is None:
        return fn(*args, **kwargs)
    from paddle_tpu.distributed import rpc

    return rpc.rpc_sync(_server_worker, fn, args=args, kwargs=kwargs)


# -- public API --------------------------------------------------------------

def init_server(tables, server_worker=None):
    """tables: {name: {"kind": "sparse"|"dense", ...SparseTable/DenseTable
    kwargs}}. With server_worker set (an rpc worker name from init_rpc),
    tables are created THERE and all pulls/pushes route over rpc; without
    it, tables are process-local (single-machine mode)."""
    global _server_worker
    _server_worker = server_worker
    for name, cfg in tables.items():
        cfg = dict(cfg)
        kind = cfg.pop("kind", "sparse")
        _call(_srv_create, name, kind, **cfg)


def shutdown_server():
    """Clears the tables WHERE THEY LIVE (over rpc in server mode), then
    detaches — server-side GBs of rows must not outlive the job."""
    global _server_worker
    _call(_srv_shutdown)
    _tables.clear()
    _server_worker = None


def get_table(name):
    """Local-mode table handle (server mode: use pull/push)."""
    return _tables.get(name)


def pull_sparse(name, ids):
    """Fetch embedding rows for ids -> np.ndarray [len(ids), dim]."""
    return _call(_srv_pull_sparse, name, np.asarray(ids))


def push_sparse(name, ids, grads, lr=None):
    """Apply SGD on the server rows: row[k] -= lr * grad."""
    return _call(_srv_push_sparse, name, np.asarray(ids),
                 np.asarray(grads, np.float32), lr)


def pull_dense(name):
    return _call(_srv_pull_dense, name)


def push_dense(name, grad, lr=None):
    return _call(_srv_push_dense, name, np.asarray(grad, np.float32), lr)
