"""Parameter-server training primitives (reference:
`paddle/fluid/distributed/ps/` service+table C++ stack and
`python/paddle/distributed/ps/` — sparse-recommendation training where
huge embedding tables live on server ranks and trainers pull/push rows).

TPU-native scope: the reference's brpc service + table zoo exists for
CPU-cluster recommendation models; on this stack the *protocol and table
semantics* are what carry the capability. This module implements, over
the framework's `distributed.rpc` (TCPStore-rendezvoused TCP):

  - `SparseTable` with pluggable per-row sparse OPTIMIZERS — sgd /
    adagrad (per-row G2Sum) / adam (per-row moments + step), the
    reference's sparse_sgd/adagrad/adam rules
    (`ps/table/sparse_sgd_rule.cc`);
  - the CTR accessor lifecycle (`ps/table/ctr_accessor.cc`): show/click
    counters per row, unseen-day aging, and `shrink()` eviction of rows
    whose decayed score drops below a threshold;
  - table `save()`/`load()` persistence (the reference's table
    save/load RPCs);
  - multi-server deployments: tables key-sharded over several rpc
    workers by hash (`ps/service/ps_client` row routing), pulls fan out
    and reassemble in order;
  - GeoSGD-style async mode: trainers keep a local cache and push
    accumulated deltas every k steps (`ps/service/communicator.cc` Geo).

Dense training should use the collective path (fleet/Engine) — this
module is for the sparse pull/push pattern.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np

__all__ = ["SparseTable", "DenseTable", "init_server", "shutdown_server",
           "pull_sparse", "push_sparse", "pull_dense", "push_dense",
           "get_table", "shrink", "save_tables", "load_tables",
           "GeoSparseCache"]


# -- sparse optimizer rules (reference ps/table/sparse_sgd_rule.cc) ---------


class _SGDRule:
    slots = 0

    def update(self, row, slot, g, lr):
        return row - lr * g, slot


class _AdagradRule:
    """Per-row accumulated squared grad (SparseAdaGradSGDRule)."""

    slots = 1

    def __init__(self, eps=1e-8):
        self.eps = eps

    def update(self, row, slot, g, lr):
        g2 = slot[0] + float(np.sum(g * g)) / max(g.size, 1)
        return row - lr * g / np.sqrt(g2 + self.eps), [g2]


class _AdamRule:
    """Per-row Adam moments (SparseAdamSGDRule)."""

    slots = 3  # m, v, step

    def __init__(self, beta1=0.9, beta2=0.999, eps=1e-8):
        self.beta1, self.beta2, self.eps = beta1, beta2, eps

    def update(self, row, slot, g, lr):
        m, v, step = slot
        step = step + 1
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        mhat = m / (1 - self.beta1 ** step)
        vhat = v / (1 - self.beta2 ** step)
        return row - lr * mhat / (np.sqrt(vhat) + self.eps), [m, v, step]


class _FtrlRule:
    """Per-row FTRL-Proximal (reference ftrl op + the PS sparse FTRL
    accessor; McMahan et al. 2013 — the classic sparse-CTR optimizer).
    Slots: z (accumulated adjusted grad), n (accumulated squared grad)."""

    slots = 2

    def __init__(self, l1=0.0, l2=0.0, lr_power=-0.5):
        self.l1, self.l2, self.lr_power = l1, l2, lr_power

    def update(self, row, slot, g, lr):
        z, n = slot
        new_n = n + g * g
        sigma = (np.power(new_n, -self.lr_power)
                 - np.power(np.maximum(n, 1e-20), -self.lr_power)) / lr
        new_z = z + g - sigma * row
        new_row = np.where(
            np.abs(new_z) <= self.l1,
            np.zeros_like(row),
            (np.sign(new_z) * self.l1 - new_z)
            / ((np.power(new_n, -self.lr_power)) / lr + 2 * self.l2))
        return new_row.astype(np.float32), [new_z, new_n]


_RULES = {"sgd": _SGDRule, "adagrad": _AdagradRule, "adam": _AdamRule,
          "ftrl": _FtrlRule}


class SparseTable:
    """Row-sharded embedding table with lazy row creation, pluggable sparse
    optimizer, and the CTR accessor lifecycle (reference
    `ps/table/memory_sparse_table.cc` + `ctr_accessor.cc`)."""

    def __init__(self, dim, initializer="uniform", init_scale=0.01, lr=0.05,
                 seed=0, optimizer="sgd", show_decay=0.98, **opt_kwargs):
        self.dim = dim
        self.seed = seed
        self.opt_kwargs = dict(opt_kwargs)
        self.lr = lr
        self.init_scale = init_scale
        self.initializer = initializer
        self.rule = _RULES[optimizer](**opt_kwargs)
        self.optimizer = optimizer
        self.show_decay = show_decay
        self._rows = {}
        self._slots = {}
        self._meta = {}  # key -> [show, click]
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()

    def _init_slot(self, g_like):
        if self.rule.slots == 0:
            return None
        if isinstance(self.rule, _AdagradRule):
            return [0.0]
        if isinstance(self.rule, _FtrlRule):
            return [np.zeros_like(g_like), np.zeros_like(g_like)]
        return [np.zeros_like(g_like), np.zeros_like(g_like), 0]

    def _row(self, key):
        key = int(key)
        r = self._rows.get(key)
        if r is None:
            if self.initializer == "zeros":
                r = np.zeros(self.dim, np.float32)
            else:
                r = self._rng.uniform(-self.init_scale, self.init_scale,
                                      self.dim).astype(np.float32)
            self._rows[key] = r
            self._meta[key] = [0.0, 0.0]
        return r

    def pull(self, ids, clicks=None, record_show=True):
        """Fetch rows; records a SHOW per pulled id (accessor semantics) and
        optional clicks. record_show=False for transport-internal pulls
        (Geo cache refresh) so CTR statistics count impressions, not
        traffic."""
        keys = np.asarray(ids).ravel()
        if keys.size == 0:  # empty feature batch: valid in sparse workloads
            return np.zeros((0, self.dim), np.float32)
        cl = np.asarray(clicks).ravel() if clicks is not None else None
        with self._lock:
            out = np.stack([self._row(k) for k in keys])
            if record_show:
                for i, k in enumerate(keys):
                    m = self._meta[int(k)]
                    m[0] += 1.0
                    if cl is not None:
                        m[1] += float(cl[i])
            return out

    def push(self, ids, grads, lr=None):
        lr = lr if lr is not None else self.lr
        grads = np.asarray(grads, np.float32)
        with self._lock:
            for k, g in zip(np.asarray(ids).ravel(), grads):
                k = int(k)
                row = self._row(k)
                slot = self._slots.get(k)
                if slot is None and self.rule.slots:
                    slot = self._init_slot(g)
                new_row, new_slot = self.rule.update(row, slot, g, lr)
                self._rows[k] = new_row.astype(np.float32)
                if self.rule.slots:
                    self._slots[k] = new_slot

    def shrink(self, threshold=1.0):
        """Decay every row's show counter and EVICT rows whose decayed show
        drops below threshold (reference MemorySparseTable::Shrink +
        CtrCommonAccessor::Shrink). Returns evicted count."""
        with self._lock:
            dead = []
            for k, m in self._meta.items():
                m[0] *= self.show_decay
                if m[0] < threshold:
                    dead.append(k)
            for k in dead:
                self._rows.pop(k, None)
                self._slots.pop(k, None)
                self._meta.pop(k, None)
            return len(dead)

    def meta(self, key):
        return tuple(self._meta.get(int(key), (0.0, 0.0)))

    def size(self):
        return len(self._rows)

    # -- persistence (reference table save/load RPCs) ----------------------
    def state(self):
        with self._lock:  # consistent snapshot vs concurrent push/shrink
            keys = np.asarray(sorted(self._rows), np.int64)
            rows = (np.stack([self._rows[int(k)].copy() for k in keys])
                    if keys.size else np.zeros((0, self.dim), np.float32))
            meta = (np.asarray([self._meta[int(k)] for k in keys],
                               np.float32)
                    if keys.size else np.zeros((0, 2), np.float32))
            st = {"keys": keys, "rows": rows, "meta": meta,
                  "optimizer": self.optimizer,
                  # construction params so a crash-restarted server can
                  # re-CREATE the table from its saved state alone
                  "config": np.asarray([self.dim, self.lr, self.init_scale,
                                        self.show_decay, self.seed],
                                       np.float64),
                  "initializer": self.initializer,
                  "opt_kwargs": json.dumps(self.opt_kwargs)}
            # optimizer slot state rides along (adagrad G2Sum / adam
            # moments+step); dropping it would make the first post-restore
            # adam push take a full-lr bias-corrected jump
            if self.optimizer == "adagrad":
                st["slot_g2"] = np.asarray(
                    [self._slots.get(int(k), [0.0])[0] for k in keys],
                    np.float32)
            elif self.optimizer == "adam":
                z = np.zeros(self.dim, np.float32)
                st["slot_m"] = (np.stack(
                    [np.asarray(self._slots.get(int(k), [z, z, 0])[0])
                     for k in keys]) if keys.size
                    else np.zeros((0, self.dim), np.float32))
                st["slot_v"] = (np.stack(
                    [np.asarray(self._slots.get(int(k), [z, z, 0])[1])
                     for k in keys]) if keys.size
                    else np.zeros((0, self.dim), np.float32))
                st["slot_step"] = np.asarray(
                    [self._slots.get(int(k), [z, z, 0])[2] for k in keys],
                    np.int64)
            elif self.optimizer == "ftrl":
                z = np.zeros(self.dim, np.float32)
                for si, sk in enumerate(("slot_z", "slot_n")):
                    st[sk] = (np.stack(
                        [np.asarray(self._slots.get(int(k), [z, z])[si])
                         for k in keys]) if keys.size
                        else np.zeros((0, self.dim), np.float32))
            return st

    def load_state(self, st):
        with self._lock:
            self._rows = {int(k): st["rows"][i].astype(np.float32)
                          for i, k in enumerate(st["keys"])}
            self._meta = {int(k): list(st["meta"][i])
                          for i, k in enumerate(st["keys"])}
            self._slots = {}
            opt = str(st.get("optimizer", "sgd"))
            if opt == self.optimizer == "adagrad" and "slot_g2" in st:
                self._slots = {int(k): [float(st["slot_g2"][i])]
                               for i, k in enumerate(st["keys"])}
            elif opt == self.optimizer == "adam" and "slot_m" in st:
                self._slots = {
                    int(k): [st["slot_m"][i].astype(np.float32),
                             st["slot_v"][i].astype(np.float32),
                             int(st["slot_step"][i])]
                    for i, k in enumerate(st["keys"])}
            elif opt == self.optimizer == "ftrl" and "slot_z" in st:
                self._slots = {
                    int(k): [st["slot_z"][i].astype(np.float32),
                             st["slot_n"][i].astype(np.float32)]
                    for i, k in enumerate(st["keys"])}

    def apply_delta(self, ids, deltas):
        """Subtract raw deltas (GeoSGD merge — bypasses the optimizer rule,
        reference communicator.cc Geo applies summed deltas directly)."""
        with self._lock:
            for k, d in zip(np.asarray(ids).ravel(),
                            np.asarray(deltas, np.float32)):
                self._rows[int(k)] = self._row(int(k)) - d


class DenseTable:
    def __init__(self, shape, lr=0.05, seed=0):
        self.value = np.random.RandomState(seed).uniform(
            -0.01, 0.01, shape).astype(np.float32)
        self.lr = lr
        self._lock = threading.Lock()

    def pull(self):
        with self._lock:
            return self.value.copy()

    def push(self, grad, lr=None):
        with self._lock:
            self.value -= (lr if lr is not None else self.lr) * np.asarray(
                grad, np.float32)

    def state(self):
        with self._lock:
            return {"value": self.value.copy(),
                    "lr": np.float64(self.lr)}

    def load_state(self, st):
        with self._lock:
            self.value = np.asarray(st["value"], np.float32)


_tables = {}
_server_workers = None  # rpc worker names hosting shards; None = local


# -- server-side functions (invoked via rpc on the server ranks) -------------

def _srv_create(name, kind, **kwargs):
    _tables[name] = (SparseTable(**kwargs) if kind == "sparse"
                     else DenseTable(**kwargs))
    return True


def _srv_pull_sparse(name, ids, clicks=None, record_show=True):
    return _tables[name].pull(ids, clicks, record_show)


def _srv_apply_delta(name, ids, deltas, req_id=None):
    if _seen_req(req_id):
        return True
    _tables[name].apply_delta(ids, deltas)
    return True


# at-least-once rpc retries must not double-apply mutations (the reply,
# not the request, may be what a transient failure lost): mutating server
# calls carry a request id and repeats are dropped (the reference brpc
# service's request dedup)
import collections as _collections

_applied_reqs = set()
_applied_order = _collections.deque()
_req_lock = threading.Lock()


def _seen_req(req_id):
    if req_id is None:
        return False
    with _req_lock:
        if req_id in _applied_reqs:
            return True
        _applied_reqs.add(req_id)
        _applied_order.append(req_id)
        if len(_applied_order) > 8192:
            _applied_reqs.discard(_applied_order.popleft())
        return False


def _srv_push_sparse(name, ids, grads, lr=None, req_id=None):
    if _seen_req(req_id):
        return True
    _tables[name].push(ids, grads, lr)
    return True


def _srv_pull_dense(name):
    return _tables[name].pull()


def _srv_push_dense(name, grad, lr=None, req_id=None):
    if _seen_req(req_id):
        return True
    _tables[name].push(grad, lr)
    return True


def _srv_shrink(name, threshold):
    return _tables[name].shrink(threshold)


def _srv_state(name):
    return _tables[name].state()


def _unstr(x, default=""):
    if x is None:
        return default
    x = np.asarray(x)
    return str(x.item()) if x.ndim == 0 else str(x)


def _srv_load_state(name, st):
    if name not in _tables:
        # crash-restarted server: re-create the table from the saved
        # construction params (reference PServer load creates tables from
        # the table proto before filling rows)
        if "value" in st:
            val = np.asarray(st["value"])
            t = DenseTable(val.shape,
                           lr=float(np.asarray(st.get("lr", 0.05))))
        else:
            cfg = np.asarray(st.get("config",
                                    [np.asarray(st["rows"]).shape[-1],
                                     0.05, 0.01, 0.98, 0]),
                             np.float64).ravel()
            okw = json.loads(_unstr(st.get("opt_kwargs"), "{}") or "{}")
            t = SparseTable(
                dim=int(cfg[0]), lr=float(cfg[1]), init_scale=float(cfg[2]),
                show_decay=float(cfg[3]),
                seed=int(cfg[4]) if cfg.size > 4 else 0,
                initializer=_unstr(st.get("initializer"), "uniform"),
                optimizer=_unstr(st.get("optimizer"), "sgd"), **okw)
        _tables[name] = t
    _tables[name].load_state(st)
    return True


def _srv_size(name):
    return _tables[name].size()


def _srv_list_tables():
    return sorted(_tables)


def _srv_shutdown():
    _tables.clear()
    return True


# how long a trainer keeps retrying a dead server shard before giving up
# (the reference communicator's send-retry window); the supervisor is
# expected to restart the server within it
_FAILOVER_TIMEOUT_S = float(os.environ.get("FLAGS_ps_failover_timeout", 60))


def _call_on(worker, fn, *args, _retry_args=None, **kwargs):
    """_retry_args: the args to use on RETRY attempts when the call is not
    idempotent under its original args (a show-recording pull)."""
    if worker is None:
        return fn(*args, **kwargs)
    import time

    from paddle_tpu.distributed import rpc

    deadline = time.time() + _FAILOVER_TIMEOUT_S
    first = True
    while True:
        try:
            use = args if (first or _retry_args is None) else _retry_args
            first = False
            return rpc.rpc_sync(worker, fn, args=use, kwargs=kwargs)
        except (ConnectionError, EOFError, OSError):
            # server shard down: keep retrying against the (possibly
            # re-published) endpoint until the supervisor restarts it —
            # PS failover (reference ps/service heartbeat + reconnect)
            if time.time() > deadline:
                raise
            time.sleep(0.5)
            try:
                rpc.refresh_worker(worker, timeout=5.0)
            except Exception:
                pass


def _shard_of(key):
    """Key routing across server shards (reference ps_client's
    `sparse_local_shard_num` hashing)."""
    if not _server_workers:
        return None
    return _server_workers[int(key) % len(_server_workers)]


def _fanout(srv_fn, name, ids, row_extras=(), extra_args=(), gather=True):
    """Route per-row calls to their hash shards — dispatched ASYNC across
    shards (one in-flight rpc per server, reference ps_client's parallel
    region requests), results reassembled in input order.

    row_extras: arrays aligned with ids, sliced per shard (grads/clicks).
    extra_args: scalars appended to every shard call (lr, flags)."""
    ids = np.asarray(ids)
    flat = ids.ravel()
    def _no_show_retry(args_tuple):
        # see result(): a retried show-recording pull must not re-count
        if srv_fn is not _srv_pull_sparse:
            return None
        base = args_tuple[:2 + len(row_extras)]
        tail = ((False,) + tuple(extra_args[1:])) if extra_args else (False,)
        return base + tail

    if not _server_workers or len(_server_workers) == 1:
        w = _server_workers[0] if _server_workers else None
        a = (name, flat, *[e for e in row_extras], *extra_args)
        return _call_on(w, srv_fn, *a, _retry_args=_no_show_retry(a))
    if flat.size == 0:  # shape must match the 1-server path ((0, dim) pulls)
        a = (name, flat, *[e for e in row_extras], *extra_args)
        return _call_on(_server_workers[0], srv_fn, *a,
                        _retry_args=_no_show_retry(a))
    parts = {}
    for i, k in enumerate(flat):
        parts.setdefault(_shard_of(k), []).append(i)
    from paddle_tpu.distributed import rpc as _rpc

    futs = []
    for w, idxs in parts.items():
        sliced = [None if e is None else np.asarray(e)[idxs]
                  for e in row_extras]
        futs.append((w, idxs, sliced, _rpc.rpc_async(
            w, srv_fn, args=(name, flat[idxs], *sliced, *extra_args))))

    def result(w, idxs, sliced, f):
        try:
            return f.wait()
        except (ConnectionError, EOFError, OSError):
            # shard died mid-flight: _call_on retries with failover. A
            # retried show-recording pull must NOT re-count the impression
            # (the server may have processed the original and only the
            # reply was lost) — retry with record_show=False; mutating
            # calls are protected by their req_id instead.
            retry = extra_args
            if srv_fn is _srv_pull_sparse:
                retry = (False,) + tuple(extra_args[1:]) if extra_args \
                    else (False,)
            return _call_on(w, srv_fn, name, flat[idxs], *sliced, *retry)

    if not gather:
        for w, idxs, sliced, f in futs:
            result(w, idxs, sliced, f)
        return True
    rows = [None] * flat.size
    for w, idxs, sliced, f in futs:
        got = result(w, idxs, sliced, f)
        for j, i in enumerate(idxs):
            rows[i] = got[j]
    return np.stack(rows)


# -- public API --------------------------------------------------------------

def init_server(tables, server_worker=None, server_workers=None):
    """tables: {name: {"kind": "sparse"|"dense", ...table kwargs}}.
    server_workers: list of rpc worker names — tables are created on EVERY
    server and sparse rows route to hash(key) % n_servers (the reference's
    multi-PServer sharding). server_worker (singular) keeps the one-server
    form. Without either, tables are process-local."""
    global _server_workers
    if server_workers is not None:
        _server_workers = list(server_workers)
    elif server_worker is not None:
        _server_workers = [server_worker]
    else:
        _server_workers = None
    targets = _server_workers or [None]
    for name, cfg in tables.items():
        cfg = dict(cfg)
        kind = cfg.pop("kind", "sparse")
        if kind == "dense":
            # dense tables have one logical copy: shard 0 only (pull_dense/
            # push_dense route there; replicas would just go stale)
            _call_on(targets[0], _srv_create, name, kind, **cfg)
        else:
            for w in targets:
                _call_on(w, _srv_create, name, kind, **cfg)


def shutdown_server():
    """Clears the tables WHERE THEY LIVE (over rpc in server mode), then
    detaches — server-side GBs of rows must not outlive the job."""
    global _server_workers
    for w in (_server_workers or [None]):
        _call_on(w, _srv_shutdown)
    _tables.clear()
    _server_workers = None


def get_table(name):
    """Local-mode table handle (server mode: use pull/push)."""
    return _tables.get(name)


def pull_sparse(name, ids, clicks=None):
    """Fetch embedding rows for ids -> np.ndarray [len(ids), dim]; rows
    route to their hash shard in multi-server mode."""
    cl = None if clicks is None else np.asarray(clicks).ravel()
    return _fanout(_srv_pull_sparse, name, ids, row_extras=(cl,))


def push_sparse(name, ids, grads, lr=None):
    """Apply the table's sparse optimizer on the server rows."""
    import uuid

    return _fanout(_srv_push_sparse, name, ids,
                   row_extras=(np.asarray(grads, np.float32),),
                   extra_args=(lr, uuid.uuid4().hex), gather=False)


def pull_dense(name):
    w = _server_workers[0] if _server_workers else None
    return _call_on(w, _srv_pull_dense, name)


def push_dense(name, grad, lr=None):
    import uuid

    w = _server_workers[0] if _server_workers else None
    return _call_on(w, _srv_push_dense, name,
                    np.asarray(grad, np.float32), lr, uuid.uuid4().hex)


def shrink(name, threshold=1.0):
    """Evict cold rows on every shard; returns total evicted."""
    return sum(_call_on(w, _srv_shrink, name, threshold)
               for w in (_server_workers or [None]))


def save_tables(path, names=None):
    """Persist tables to `path` (one npz per table per shard — the
    reference's table save RPC fan-out). Without explicit names the
    server(s) are ASKED what they host (works in local, single-server rpc,
    and sharded modes)."""
    os.makedirs(path, exist_ok=True)
    workers = _server_workers or [None]
    if names is None:
        names = sorted({n for w in workers
                        for n in _call_on(w, _srv_list_tables)})
    for name in names:
        for si, w in enumerate(workers):
            try:
                st = _call_on(w, _srv_state, name)
            except KeyError:
                continue  # dense tables live on shard 0 only
            np.savez(os.path.join(path, f"{name}.shard{si}.npz"), **st)


def load_tables(path, names=None):
    """Load tables saved by save_tables. The saved shard count may differ
    from the current server count: ALL saved shards are read, merged, and
    re-sharded by the CURRENT hash routing (the reference's load with
    changed pserver count re-distributes rows the same way)."""
    workers = _server_workers or [None]
    for name, merged in _shard_states_from_dir(path, names).items():
        if "value" in merged:  # dense table: single logical state
            _call_on(workers[0], _srv_load_state, name, merged)
            continue
        if len(workers) == 1:
            _call_on(workers[0], _srv_load_state, name, merged)
            continue
        for wi, w in enumerate(workers):
            _call_on(w, _srv_load_state, name,
                     _route_shard(merged, wi, len(workers)))


def _shard_states_from_dir(path, names=None):
    """{table: merged logical state} from a save_tables dir — THE single
    reader for every load path (trainer reshard-load, rejoined-server
    local load, targeted reload)."""
    if names is None:
        names = sorted({f.split(".shard")[0] for f in os.listdir(path)
                        if ".shard" in f})
    out = {}
    for tname in names:
        shard_files = sorted(
            f for f in os.listdir(path)
            if f.startswith(tname + ".shard") and f.endswith(".npz"))
        if not shard_files:
            raise FileNotFoundError(f"no shards for table {tname} in {path}")
        states = [dict(np.load(os.path.join(path, f))) for f in shard_files]
        out[tname] = (states[0] if "value" in states[0]
                      else _merge_sparse_states(states))
    return out


def _route_shard(merged, shard_index, n_shards):
    """The rows shard `shard_index` owns under the current hash routing."""
    sel = np.asarray([i for i, k in enumerate(merged["keys"])
                      if int(k) % n_shards == shard_index], np.int64)
    return _select_rows(merged, sel)


def _select_rows(merged, sel):
    """Row-subset of a merged sparse state; per-table metadata
    (optimizer/config/initializer) passes through un-sliced."""
    meta = ("optimizer", "config", "initializer", "opt_kwargs", "lr")
    out = {k: v[sel] for k, v in merged.items()
           if isinstance(v, np.ndarray) and k not in meta}
    for k in meta:
        if k in merged:
            out[k] = merged[k]
    return out


def _merge_sparse_states(states):
    """Concatenate per-shard sparse states into one logical table state
    (per-table metadata — optimizer/config/initializer — passes through
    from shard 0, it is identical on every shard)."""
    out = {}
    arr_keys = [k for k in states[0] if isinstance(states[0][k], np.ndarray)
                and states[0][k].ndim >= 1 and k not in ("config",)]
    for k in arr_keys:
        out[k] = np.concatenate([st[k] for st in states])
    opt = states[0].get("optimizer", "sgd")
    out["optimizer"] = (opt.item() if hasattr(opt, "item") else opt)
    for meta_k in ("config", "initializer", "opt_kwargs"):
        if meta_k in states[0]:
            out[meta_k] = states[0][meta_k]
    return out


def _geo_apply_delta(name, ids, deltas):
    import uuid

    return _fanout(_srv_apply_delta, name, ids,
                   row_extras=(np.asarray(deltas, np.float32),),
                   extra_args=(uuid.uuid4().hex,), gather=False)


def _pull_no_show(name, ids):
    return _fanout(_srv_pull_sparse, name, ids, row_extras=(None,),
                   extra_args=(False,))


class GeoSparseCache:
    """GeoSGD async mode (reference `ps/service/communicator.cc` Geo): the
    trainer applies updates to a LOCAL row cache and pushes accumulated
    deltas to the server every `k_steps`; pulls refresh the cache."""

    def __init__(self, name, dim, k_steps=4, lr=0.05):
        self.name = name
        self.dim = dim
        self.k_steps = k_steps
        self.lr = lr
        self._cache = {}
        self._delta = {}
        self._step = 0

    def pull(self, ids):
        keys = np.asarray(ids).ravel()
        missing = [k for k in keys if int(k) not in self._cache]
        if missing:
            rows = pull_sparse(self.name, np.asarray(missing))
            for k, r in zip(missing, rows):
                self._cache[int(k)] = r.copy()
        return np.stack([self._cache[int(k)] for k in keys])

    def push(self, ids, grads):
        """Local SGD apply + delta accumulation; auto-syncs every k_steps.
        Ids never pulled locally are fetched first (lazy, matching the
        server table's lazy row creation)."""
        keys = np.asarray(ids).ravel()
        missing = np.asarray([k for k in keys if int(k) not in self._cache],
                             np.int64)
        if missing.size:
            self.pull(missing)
        for k, g in zip(keys, np.asarray(grads)):
            k = int(k)
            upd = self.lr * np.asarray(g, np.float32)
            self._cache[k] = self._cache[k] - upd
            self._delta[k] = self._delta.get(
                k, np.zeros(self.dim, np.float32)) + upd
        self._step += 1
        if self._step % self.k_steps == 0:
            self.sync()

    def sync(self):
        """Apply accumulated deltas on the server via the RAW-delta path
        (bypassing the table's optimizer rule — Geo deltas are already
        optimizer-applied locally; feeding them through adam/adagrad would
        renormalize them into something unrelated)."""
        if not self._delta:
            return
        keys = np.asarray(sorted(self._delta), np.int64)
        deltas = np.stack([self._delta[int(k)] for k in keys])
        _geo_apply_delta(self.name, keys, deltas)
        self._delta.clear()
        # refresh cache from authoritative rows; transport pull — does NOT
        # count as a show (CTR stats track impressions, not traffic)
        rows = _pull_no_show(self.name, keys)
        for k, r in zip(keys, rows):
            self._cache[int(k)] = r.copy()
