"""PS server-process lifecycle (reference `ps/service/`: the brpc PServer
runs as its own process with start/stop/load RPCs; `ps/service/server.cc`
StartServer/StopServer).

TPU-native scope: a PS server here is an rpc worker process whose only job
is hosting table shards. This module gives it a lifecycle — serve until a
`stop_serving` rpc arrives, rejoin the rpc world after a crash-restart
(fresh port, same rank), and reload its shard from a `save_tables` dir —
plus the trainer-side helpers. Worker pull/push failover (retry + endpoint
refresh) lives in `_call_on`/`_fanout`.
"""

from __future__ import annotations

import threading

import numpy as np

from paddle_tpu.distributed import ps

__all__ = ["serve", "stop_serving", "reload_shard"]

_stop = threading.Event()


def _srv_stop_serving():
    _stop.set()
    return True


def serve(name, rank, world_size, master_endpoint=None, rejoin=False,
          load_path=None, shard_index=None, n_shards=None):
    """Run THIS process as a PS server until a stop_serving() rpc arrives.

    rejoin=True (crash-restart): re-publish this rank's endpoint without
    the init barrier. load_path: reload this server's rows from a
    save_tables dir — with shard_index/n_shards the merged save is
    filtered to the keys this shard owns under the current hash routing
    (the reference's PServer load RPC)."""
    from paddle_tpu.distributed import rpc

    # load BEFORE the endpoint goes live: a retrying trainer must never
    # reach a rejoined server whose tables aren't there yet
    if load_path is not None:
        _load_local_shard(load_path, shard_index, n_shards)
    rpc.init_rpc(name, rank=rank, world_size=world_size,
                 master_endpoint=master_endpoint, rejoin=rejoin)
    _stop.wait()
    rpc.shutdown()


def _load_local_shard(path, shard_index, n_shards):
    """Load THIS process's shard of every saved table directly into the
    local registry (no rpc — we ARE the server)."""
    for tname, merged in ps._shard_states_from_dir(path).items():
        if "value" in merged:  # dense: shard 0 only
            if not shard_index:
                ps._srv_load_state(tname, merged)
            continue
        if shard_index is not None and n_shards and n_shards > 1:
            merged = ps._route_shard(merged, shard_index, n_shards)
        ps._srv_load_state(tname, merged)


def stop_serving(worker):
    """Trainer-side: release a server process from serve()."""
    return ps._call_on(worker, _srv_stop_serving)


def reload_shard(path, worker, shard_index, n_shards, names=None):
    """Trainer-side targeted reload: push the rows shard `shard_index`
    owns (under the current routing) from a save_tables dir to `worker` —
    the recovery half of failover when the restarted server was started
    without load_path."""
    for tname, merged in ps._shard_states_from_dir(path, names).items():
        if "value" in merged:
            if shard_index == 0:
                ps._call_on(worker, ps._srv_load_state, tname, merged)
            continue
        ps._call_on(worker, ps._srv_load_state, tname,
                    ps._route_shard(merged, shard_index, n_shards))
