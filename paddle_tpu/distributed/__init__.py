"""paddle_tpu.distributed: the distributed stack, TPU-native.

Reference surface: `python/paddle/distributed/` (155K LoC). The reference
stacks Python collectives on per-rank NCCL communicators
(`collective.py:151-180`); here everything compiles to XLA collectives over a
`jax.sharding.Mesh` (ICI/DCN), with a single-controller runtime.

Layout:
  process_mesh / placement / api   — DTensor-style semi-auto parallel
  collective / communication       — groups + functional collectives
  parallel                         — init_parallel_env, DataParallel
  fleet                            — hybrid parallel (dp/mp/pp/sharding/sep)
  checkpoint                       — sharded save/load with reshard-on-load
  launch                           — process launcher CLI (multi-host)
"""

from paddle_tpu.distributed.process_mesh import (  # noqa: F401
    ProcessMesh, get_mesh, set_mesh, init_mesh,
)
from paddle_tpu.distributed.placement import (  # noqa: F401
    Placement, Shard, Replicate, Partial,
)
from paddle_tpu.distributed.api import (  # noqa: F401
    shard_tensor, reshard, shard_layer, dtensor_from_fn, unshard_dtensor,
    get_placements, is_dist_tensor,
)
from paddle_tpu.distributed.collective import (  # noqa: F401
    Group, new_group, get_group, is_initialized, destroy_process_group,
)
from paddle_tpu.distributed.communication import (  # noqa: F401
    ReduceOp, all_reduce, all_gather, all_gather_object, reduce, broadcast,
    scatter, reduce_scatter, alltoall, alltoall_single, send, recv, isend,
    irecv, barrier, get_backend, P2POp, batch_isend_irecv,
)
from paddle_tpu.distributed.parallel import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, ParallelEnv, DataParallel,
)
from paddle_tpu.distributed.engine import Engine  # noqa: F401
from paddle_tpu.distributed.mesh_utils import (  # noqa: F401
    create_hybrid_mesh, slice_count)
from paddle_tpu.distributed.pipeline_engine import (  # noqa: F401
    PipelineEngine, transformer_mp_spec,
)
from paddle_tpu.distributed.ring_attention import (  # noqa: F401
    ring_attention, ulysses_attention,
)


import importlib as _importlib

_LAZY_SUBMODULES = ("fleet", "checkpoint", "launch", "sharding", "utils",
                    "auto_parallel", "rpc", "ps")


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        try:
            mod = _importlib.import_module(f"paddle_tpu.distributed.{name}")
        except ModuleNotFoundError as e:
            if e.name == f"paddle_tpu.distributed.{name}":
                raise AttributeError(
                    f"module 'paddle_tpu.distributed' has no attribute "
                    f"{name!r}") from e
            raise  # a real missing dependency inside the submodule
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'paddle_tpu.distributed' has no attribute {name!r}")
from paddle_tpu.distributed.api_extras import *  # noqa: F401,F403,E402
from paddle_tpu.distributed.checkpoint import (  # noqa: F401,E402
    CheckpointManager, load_state_dict, save_state_dict,
)
from paddle_tpu.distributed.nonfinite_guard import (  # noqa: F401,E402
    NonFiniteError, NonFiniteGuard,
)
from paddle_tpu.distributed import io  # noqa: F401,E402
