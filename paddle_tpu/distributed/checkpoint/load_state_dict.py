"""Distributed checkpoint load with reshard-on-load.

Reference: `python/paddle/distributed/checkpoint/load_state_dict.py` — reads
the global Metadata, figures out which saved shards intersect each local
shard, and reassembles. Here the saved value is logical, so "reshard" is one
`jax.device_put` onto each destination tensor's *current* sharding — loading
a checkpoint saved under dp2/mp4 into a dp4/mp2 run just works.
"""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.distributed.checkpoint.metadata import Metadata
from paddle_tpu.distributed.checkpoint.save_state_dict import (
    _META_FILE, _flatten_state)


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, offload=False):
    """Fill `state_dict`'s tensors in place from `path`."""
    import jax

    from paddle_tpu.core.tensor import Tensor

    md = Metadata.load(os.path.join(path, _META_FILE))
    flat = _flatten_state(state_dict)
    missing = [k for k in flat if k not in md.tensors]
    if missing:
        raise ValueError(f"checkpoint at {path} is missing tensors {missing[:5]}"
                         f"{'...' if len(missing) > 5 else ''}")
    for name, t in flat.items():
        tm = md.tensors[name]
        host = np.load(os.path.join(path, tm.file))
        if isinstance(t, Tensor):
            if list(host.shape) != list(t.shape):
                raise ValueError(
                    f"{name}: saved shape {list(host.shape)} != target "
                    f"{list(t.shape)}")
            sharding = getattr(t._data, "sharding", None)
            arr = (jax.device_put(host.astype(t._data.dtype), sharding)
                   if sharding is not None else
                   jax.numpy.asarray(host.astype(t._data.dtype)))
            t._data = arr
        elif hasattr(t, "sharding"):  # bare jax.Array in the dict
            state_dict_set(state_dict, name,
                           jax.device_put(host, t.sharding))
    return state_dict


def state_dict_set(state_dict, dotted, value):
    parts = dotted.split(".")
    d = state_dict
    for p in parts[:-1]:
        d = d[p]
    d[parts[-1]] = value
